"""Quantized + ring-overlapped explicit collectives for ZeRO/FSDP.

At scale the wire bill moves from the data-parallel gradient all-reduce
(compressible via ``--grad-compress``, :mod:`..train.compress`) to the
FSDP **param all-gathers and grad reduce-scatters**, which the annotation
path (:mod:`.zero`) leaves to XLA's partitioner: full fp32, no overlap
control.  This module owns that dataflow instead, three layers deep:

1. **Wire formats** — :func:`all_gather` / :func:`reduce_scatter` run
   under ``shard_map`` with an explicit ``method``: ``bf16`` (half the
   bytes, exponent range kept) or common-scale symmetric ``int8`` (one
   global ``pmax`` scale per leaf, EQuARX-style numerics — see
   PAPERS.md).  ``int8`` composes with momentum/Adam through per-leaf
   **error-feedback residuals** (:func:`ef_quantize`): the quantization
   error of step *t* is added back before quantizing step *t+1*, so the
   applied updates telescope to the true sum instead of accumulating
   bias.  As in :mod:`..train.compress`, the int8 *reduction* is
   emulated in int32 at framework level (the true wire format needs
   compiler support); the all-gather variants genuinely move int8/bf16
   buffers.
2. **Ring overlap** — ``overlap=True`` swaps each collective for a
   double-buffered ``ppermute`` ring (the decomposition idiom of arxiv
   2112.01075, same loop shape as :mod:`.ring_attention`): the transfer
   for chunk *k+1* is issued **before** chunk *k*'s consumer op, so XLA
   may pipeline the next hop's wire time under the current chunk's
   compute.  :func:`gather_matmul` is the fused consumer form — each
   arriving param chunk feeds its matmul rows immediately, never
   materialising the gathered operand.
3. **The FSDP step** — :func:`make_fsdp_step_fns` is the explicit-
   collective rendition of ZeRO-3: gather params → forward/backward →
   reduce-scatter grads → sharded optimizer update, with the residual
   threaded through ``TrainState.comm_residual``.  ``method="none"``
   reproduces the :mod:`.zero` annotation path's numerics (the parity
   gate bench.py's ``collectives`` record measures).

In uncompressed mode every variant is value-equal to its XLA primitive
(``lax.all_gather`` / ``lax.psum_scatter``); ring reductions only
reassociate the sum, so bit-parity holds whenever the addition is exact.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_learning_tpu.data.loader import BATCH_AXES
from distributed_deep_learning_tpu.runtime.shmap import shard_map
from distributed_deep_learning_tpu.train.objectives import prediction_metrics

METHODS = ("none", "bf16", "int8")

#: claimed wire bytes per element (the format a compiler-level
#: implementation would put on the ICI; the analytic accounting
#: :func:`wire_bytes` uses)
WIRE_ITEMSIZE = {"bf16": 2, "int8": 1}

#: int8 ships one f32 scale per leaf per collective
_SCALE_BYTES = 4

#: reduction accumulator per method: int32 keeps int8 sums exact up to
#: 2^24 shards; bf16 values accumulate in f32 (psum upcasts on TPU)
_ACCUM = {"bf16": jnp.float32, "int8": jnp.int32}


# --------------------------------------------------------------------------
# wire formats
# --------------------------------------------------------------------------

def quantize(x, method: str, axis=None):
    """``x`` → ``(wire, scale)``.  For int8 the scale is the GLOBAL
    max-|x| over ``axis`` (one scalar pmax) so every shard dequantizes
    identically; ``axis=None`` quantizes with the local amax (for use
    outside shard_map)."""
    if method == "none":
        return x, None
    if method == "bf16":
        return x.astype(jnp.bfloat16), None
    if method == "int8":
        amax = jnp.max(jnp.abs(x))
        if axis is not None:
            amax = lax.pmax(amax, axis)
        scale = jnp.maximum(amax / 127.0, jnp.asarray(1e-30, x.dtype))
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return q, scale
    raise ValueError(f"unknown comm method {method!r}; "
                     f"choose from {METHODS}")


def dequantize(wire, scale, method: str, dtype):
    if method == "none":
        return wire
    if method == "bf16":
        return wire.astype(dtype)
    return wire.astype(dtype) * scale


def ef_quantize(x, residual, method: str, axis=None):
    """Error-feedback quantization: ``(wire, scale, new_residual)``.

    The residual (last step's quantization error) is added back before
    quantizing, and the new error is returned to carry forward — the sum
    of dequantized outputs telescopes to the true sum of inputs, so the
    compression is unbiased in the long run instead of per step.
    ``residual=None`` (or ``method="none"``) degrades to plain
    :func:`quantize`."""
    if method == "none" or residual is None:
        wire, scale = quantize(x, method, axis)
        return wire, scale, residual
    v = x + residual.astype(x.dtype)
    wire, scale = quantize(v, method, axis)
    new_res = v - dequantize(wire, scale, method, x.dtype)
    return wire, scale, new_res


# --------------------------------------------------------------------------
# ring variants (shard_map-internal; same ppermute-in-scan shape as
# ring_attention.py)
# --------------------------------------------------------------------------

def _ring_all_gather(wire, axis: str, size: int):
    """Ring all-gather of dim-0 blocks: ``(m, ...)`` → ``(size*m, ...)``.

    Double-buffered: the ppermute for hop *r+1* is issued before hop
    *r*'s block is consumed (here the buffer write; in
    :func:`gather_matmul` the consumer matmul), so the next transfer is
    in flight while the current block is used."""
    S = size
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % S) for i in range(S)]
    out = jnp.zeros((S,) + wire.shape, wire.dtype).at[my].set(wire)
    blk = lax.ppermute(wire, axis, perm)  # hop 1, issued up front

    def hop(carry, r):   # blk = hop r's block, not yet consumed
        out, blk = carry
        nxt = lax.ppermute(blk, axis, perm)     # hop r+1 in flight...
        out = out.at[(my - r) % S].set(blk)     # ...while hop r lands
        return (out, nxt), None

    if S > 2:
        (out, blk), _ = lax.scan(hop, (out, blk), jnp.arange(1, S - 1))
    out = out.at[(my - (S - 1)) % S].set(blk)
    return out.reshape((S * wire.shape[0],) + wire.shape[1:])


def _ring_reduce_scatter(contrib, axis: str, size: int):
    """Ring reduce-scatter: ``(size*m, ...)`` per-shard contributions →
    this shard's reduced ``(m, ...)`` chunk.

    The partial sum for chunk *j* starts at shard *j+1* and travels the
    ring collecting each shard's contribution; at every hop the
    ppermute is issued before the consumer add of the next local chunk,
    so the wire and the adds pipeline."""
    S = size
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % S) for i in range(S)]
    m = contrib.shape[0] // S
    blocks = contrib.reshape((S, m) + contrib.shape[1:])
    send = blocks[(my - 1) % S]   # chunk my-1's partial: own contribution

    def hop(send, r):
        recvd = lax.ppermute(send, axis, perm)      # hop r in flight...
        return recvd + blocks[(my - 1 - r) % S], None   # ...then the add

    acc, _ = lax.scan(hop, send, jnp.arange(1, S))
    return acc   # chunk `my`, fully reduced


# --------------------------------------------------------------------------
# the collectives
# --------------------------------------------------------------------------

def all_gather(x, axis: str, *, size: int, method: str = "none",
               overlap: bool = False, residual=None):
    """Explicit all-gather of dim-0 blocks under shard_map:
    ``(m, ...)`` → ``(size*m, ...)``, quantized on the wire per
    ``method``, ring-overlapped when ``overlap``.  Every shard
    dequantizes the same wire values (common scale), so the gathered
    array is replicated-consistent.  With ``residual`` returns
    ``(gathered, new_residual)``."""
    wire, scale, new_res = ef_quantize(x, residual, method, axis)
    if size == 1:
        gathered = wire
    elif overlap:
        gathered = _ring_all_gather(wire, axis, size)
    else:
        gathered = lax.all_gather(wire, axis, tiled=True)
    out = dequantize(gathered, scale, method, x.dtype)
    return out if residual is None else (out, new_res)


def reduce_scatter(x, axis: str, *, size: int, method: str = "none",
                   overlap: bool = False, residual=None):
    """Explicit reduce-scatter under shard_map: ``(size*m, ...)`` local
    contributions → this shard's summed ``(m, ...)`` chunk.  The local
    contribution is quantized ONCE (with error feedback when
    ``residual`` is given); partials accumulate in int32/f32 so ring
    and XLA reductions agree exactly for int8.  With ``residual``
    returns ``(chunk, new_residual)``."""
    wire, scale, new_res = ef_quantize(x, residual, method, axis)
    contrib = wire if method == "none" else wire.astype(_ACCUM[method])
    if size == 1:
        acc = contrib
    elif overlap:
        acc = _ring_reduce_scatter(contrib, axis, size)
    else:
        acc = lax.psum_scatter(contrib, axis, tiled=True)
    if method == "none":
        out = acc
    elif method == "bf16":
        out = acc.astype(x.dtype)
    else:
        out = acc.astype(x.dtype) * scale
    return out if residual is None else (out, new_res)


def gather_matmul(a_block, b, axis: str, *, size: int, method: str = "none",
                  overlap: bool = False):
    """``all_gather(a) @ b`` with the ring's consumer fused in:
    ``a_block (m, k)`` per shard, ``b (k, n)`` replicated →
    ``(size*m, n)``.  With ``overlap`` each arriving chunk's matmul runs
    while the next chunk's ppermute is already issued — the gathered
    operand is never materialised.  The tentpole overlap demo
    ``scripts/comm_bench.py`` times."""
    wire, scale = quantize(a_block, method, axis)
    S = size
    if S == 1 or not overlap:
        full = wire if S == 1 else lax.all_gather(wire, axis, tiled=True)
        return dequantize(full, scale, method, a_block.dtype) @ b

    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % S) for i in range(S)]
    m = wire.shape[0]
    out = jnp.zeros((S, m, b.shape[1]), b.dtype)
    out = out.at[my].set(dequantize(wire, scale, method, a_block.dtype) @ b)
    blk = lax.ppermute(wire, axis, perm)

    def hop(carry, r):
        out, blk = carry
        nxt = lax.ppermute(blk, axis, perm)     # chunk r+1 in flight...
        chunk = dequantize(blk, scale, method, a_block.dtype)
        out = out.at[(my - r) % S].set(chunk @ b)   # ...during chunk r's matmul
        return (out, nxt), None

    if S > 2:
        (out, blk), _ = lax.scan(hop, (out, blk), jnp.arange(1, S - 1))
    chunk = dequantize(blk, scale, method, a_block.dtype)
    out = out.at[(my - (S - 1)) % S].set(chunk @ b)
    return out.reshape((S * m, b.shape[1]))


# --------------------------------------------------------------------------
# analytic wire accounting (host-side; a jitted program cannot count its
# own bytes, and the int8 reduction is int32-emulated anyway — these are
# the bytes the CLAIMED wire format moves)
# --------------------------------------------------------------------------

def wire_bytes(op: str, method: str, shape, axis_size: int,
               itemsize: int = 4) -> int:
    """Bytes one shard SENDS for one collective.  ``shape`` is the local
    block for ``all_gather`` and the full input for ``reduce_scatter``;
    ring and bidirectional XLA schedules both move (S-1)/S of the data
    per shard.  ``kv_migrate`` is point-to-point (the serve tier's
    KV-block migration): one sender, one receiver, the payload crosses
    the fabric exactly once — no (S-1)/S schedule factor."""
    elems = int(math.prod(shape)) if shape else 1
    if op == "kv_migrate":
        size = WIRE_ITEMSIZE.get(method, itemsize)
        return elems * size + (_SCALE_BYTES if method == "int8" else 0)
    if op == "reduce_scatter":
        elems //= max(1, axis_size)
    sent = elems * (axis_size - 1)
    size = WIRE_ITEMSIZE.get(method, itemsize)
    return sent * size + (_SCALE_BYTES if method == "int8" else 0)


def tree_wire_bytes(op: str, method: str, tree, axis_size: int) -> int:
    """Sum of :func:`wire_bytes` over a pytree of arrays/shapes."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", leaf)
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        total += wire_bytes(op, method, tuple(shape), axis_size, itemsize)
    return total


def fsdp_wire_stats(params, dims, axis_size: int, method: str) -> dict:
    """Per-step analytic wire bytes for the explicit FSDP dataflow (one
    param all-gather + one grad reduce-scatter over the leaves ``dims``
    marks as sharded), plus the fp32 bytes the same collectives would
    move — the ratio the bench's >=3x acceptance gate checks."""
    gather = scatter = gather_fp32 = scatter_fp32 = 0
    for leaf, d in zip(jax.tree.leaves(params), jax.tree.leaves(dims)):
        if d < 0:
            continue
        shape = tuple(leaf.shape)
        block = tuple(s // axis_size if i == d else s
                      for i, s in enumerate(shape))
        gather += wire_bytes("all_gather", method, block, axis_size)
        scatter += wire_bytes("reduce_scatter", method, shape, axis_size)
        gather_fp32 += wire_bytes("all_gather", "none", block, axis_size)
        scatter_fp32 += wire_bytes("reduce_scatter", "none", shape,
                                   axis_size)
    return {"all_gather_bytes": gather, "reduce_scatter_bytes": scatter,
            "all_gather_fp32_bytes": gather_fp32,
            "reduce_scatter_fp32_bytes": scatter_fp32}


# --------------------------------------------------------------------------
# error-feedback state
# --------------------------------------------------------------------------

def attach_residual(state, n_shards: int):
    """Zero-init the per-shard error-feedback buffer on
    ``TrainState.comm_residual``: one params-shaped tree with a leading
    per-shard axis, sharded over the batch axes (each device carries
    exactly its own residual).  Attach BEFORE deriving sharding specs —
    :mod:`.zero`'s builders map the field alongside the rest."""
    res = jax.tree.map(
        lambda p: jnp.zeros((n_shards,) + tuple(p.shape), p.dtype),
        state.params)
    return state.replace(comm_residual=res)


def residual_spec(tree):
    """PartitionSpecs for a residual tree: leading axis over the batch
    axes, everything else replicated."""
    return jax.tree.map(lambda _: P(BATCH_AXES), tree)


# --------------------------------------------------------------------------
# the explicit-collective FSDP step
# --------------------------------------------------------------------------

def _spec_dim(spec: P, axis: str) -> int:
    """Which dim ``spec`` shards over ``axis`` (-1 = replicated)."""
    for i, entry in enumerate(spec):
        names = entry if isinstance(entry, tuple) else (entry,)
        if axis in names:
            return i
    return -1


def make_fsdp_step_fns(mesh: Mesh, loss_fn: Callable, *, state_spec,
                       method: str = "none", overlap: bool = False,
                       axis: str = "fsdp", remat: bool = False,
                       remat_policy: str = "nothing",
                       batch_spec: P = P(BATCH_AXES), registry=None):
    """(train_step, eval_step) owning the FSDP collectives explicitly.

    Where :mod:`.zero` hands XLA a sharded spec and trusts the
    partitioner, this builder writes the ZeRO-3 dataflow out: all-gather
    the sharded params (quantized per ``method``, ring-overlapped per
    ``overlap``) → forward/backward on the local batch shard →
    reduce-scatter the grads back into the shard (with error feedback
    when ``state.comm_residual`` is attached) → update params+optimizer
    shard-local.  ``state_spec`` is the same TrainState-shaped spec tree
    :func:`..parallel.zero.fsdp_state_spec` produces — leaves it left
    replicated (small/indivisible) skip the gather and psum their grads
    uncompressed.

    ``method="none"`` is loss-parity with the annotation path (the
    bench gate); the optimizer must be elementwise (sgd/momentum/adam —
    a global-norm clip would need its own psum, which shard-local
    ``tx.update`` does not insert).  ``registry`` (an
    ``obs.metrics.MetricsRegistry``) gets per-step ``comm_bytes{op,
    method}`` counters, incremented host-side from the analytic model.
    """
    if method not in METHODS:
        raise ValueError(f"unknown comm method {method!r}; "
                         f"choose from {METHODS}")
    from distributed_deep_learning_tpu.train.step import _remat_policy

    policy = _remat_policy(remat_policy)   # eager: fail fast on typos
    S = mesh.shape.get(axis, 1)
    if S <= 1:
        raise ValueError(f"explicit FSDP collectives need a >1 {axis!r} "
                         "mesh axis (nothing to gather/scatter)")
    batch_axes = tuple(a for a in BATCH_AXES if mesh.shape.get(a, 1) > 1)
    other_axes = tuple(a for a in batch_axes if a != axis)
    n_batch = 1
    for a in batch_axes:
        n_batch *= mesh.shape[a]

    # which dim each param leaf shards over `axis` (-1 = replicated);
    # static, precomputed from the spec tree the annotation path uses
    gdims = jax.tree.map(lambda s: _spec_dim(s, axis), state_spec.params)

    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, batch_spec)
    state_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), state_spec)

    def _gather_leaf(p, d):
        if d < 0:
            return p
        x0 = jnp.moveaxis(p, d, 0)
        g = all_gather(x0, axis, size=S, method=method, overlap=overlap)
        return jnp.moveaxis(g, 0, d)

    def train_step(state, x, y):
        has_rng = state.rng is not None
        has_res = state.comm_residual is not None
        key = jax.random.fold_in(state.rng, state.step) if has_rng \
            else jax.random.key(0)

        def compute(params, ms, key, x, y):
            rngs = {"dropout": key} if has_rng else None
            fwd = state.apply_fn
            if remat:
                fwd = jax.checkpoint(lambda p, m, xx: state.apply_fn(
                    p, m, xx, train=True, rngs=rngs), policy=policy)
                pred, new_ms, aux = fwd(params, ms, x)
            else:
                pred, new_ms, aux = fwd(params, ms, x, train=True, rngs=rngs)
            loss = loss_fn(pred, y)
            return loss + aux, (prediction_metrics(pred, y, loss), new_ms)

        @partial(shard_map, mesh=mesh,
                 in_specs=(state_spec, P(), batch_spec, batch_spec),
                 out_specs=(state_spec, P()), check_vma=False)
        def step(st, key, xx, yy):
            if has_rng:
                # each batch shard draws an INDEPENDENT dropout mask
                for a in batch_axes:
                    key_local = jax.random.fold_in(key, lax.axis_index(a))
                    key = key_local
            full_params = jax.tree.map(_gather_leaf, st.params, gdims)
            (_, (metrics, new_ms)), g = jax.value_and_grad(
                compute, has_aux=True)(full_params, st.model_state, key,
                                       xx, yy)
            if other_axes:
                # fold the non-shard batch axes first; the scatter below
                # finishes the reduction over `axis`
                g = jax.tree.map(lambda l: lax.psum(l, other_axes), g)

            res = st.comm_residual
            if has_res:
                res = jax.tree.map(lambda r: jnp.squeeze(r, 0), res)

            def scatter(gl, d, rl):
                if d < 0:   # replicated leaf: plain full-precision psum
                    return lax.psum(gl, (axis,)), rl
                g0 = jnp.moveaxis(gl, d, 0)
                r0 = None if rl is None else jnp.moveaxis(rl, d, 0)
                if r0 is None:
                    out = reduce_scatter(g0, axis, size=S, method=method,
                                         overlap=overlap)
                else:
                    out, r0 = reduce_scatter(g0, axis, size=S,
                                             method=method, overlap=overlap,
                                             residual=r0)
                    rl = jnp.moveaxis(r0, 0, d)
                return jnp.moveaxis(out, 0, d), rl

            if has_res:
                pairs = jax.tree.map(scatter, g, gdims, res)
            else:
                pairs = jax.tree.map(lambda gl, d: scatter(gl, d, None),
                                     g, gdims)
            is_pair = lambda t: isinstance(t, tuple)  # noqa: E731
            g = jax.tree.map(lambda t: t[0] / n_batch, pairs,
                             is_leaf=is_pair)
            new_res = st.comm_residual
            if has_res:
                new_res = jax.tree.map(lambda t: t[1][None], pairs,
                                       is_leaf=is_pair)

            metrics = {  # loss is a shard mean → average; counts sum
                "loss": lax.psum(metrics["loss"], batch_axes) / n_batch,
                "correct": lax.psum(metrics["correct"], batch_axes),
                "count": lax.psum(metrics["count"], batch_axes),
            }
            new_ms = jax.tree.map(
                lambda s: lax.psum(s.astype(jnp.float32),
                                   batch_axes) / n_batch
                if jnp.issubdtype(s.dtype, jnp.floating) else s, new_ms)

            updates, new_opt = st.tx.update(g, st.opt_state, st.params)
            new_params = optax.apply_updates(st.params, updates)
            new_state = st.replace(step=st.step + 1, params=new_params,
                                   opt_state=new_opt, model_state=new_ms,
                                   comm_residual=new_res)
            return new_state, metrics

        return step(state, key, x, y)

    def eval_step(state, x, y):
        # eval gathers via the annotation path: the partitioner inserts
        # the all-gathers from the sharded in_shardings
        pred, _, _ = state.apply_fn(state.params, state.model_state, x,
                                    train=False)
        return prediction_metrics(pred, y, loss_fn(pred, y))

    train_step = jax.jit(train_step,
                         in_shardings=(state_sh, batch_sh, batch_sh),
                         out_shardings=(state_sh, repl),
                         donate_argnums=(0,))
    eval_step = jax.jit(eval_step,
                        in_shardings=(state_sh, batch_sh, batch_sh),
                        out_shardings=repl)

    if registry is None:
        return train_step, eval_step

    stats: dict = {}

    def train_step_counted(state, x, y):
        if not stats:
            stats.update(fsdp_wire_stats(state.params, gdims, S, method))
        registry.counter("comm_bytes", op="all_gather", method=method).inc(
            stats["all_gather_bytes"])
        registry.counter("comm_bytes", op="reduce_scatter",
                         method=method).inc(stats["reduce_scatter_bytes"])
        return train_step(state, x, y)

    # keep AOT hooks (FLOPs measurement, trial compile) working through
    # the counting wrapper
    train_step_counted.lower = train_step.lower
    return train_step_counted, eval_step
