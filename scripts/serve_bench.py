"""Serving bench CLI: continuous batching, paged KV, prefix reuse, SLOs.

Thin driver over ``serve/bench.py`` — ALL load shapes and harness logic
live there; this script only parses flags and prints ONE JSON line to
stdout (human-readable latency summary to stderr).

Two modes:

* default — the v1 A/B: a seeded mixed-length trace through the
  slot-based continuous-batching engine AND the batch-synchronous
  run-to-completion ``generate()`` baseline (``serving_bench``).
* ``--paged`` — the second-generation bench (``paged_serving_bench``):
  a trace-driven SLO load (Poisson/bursty arrivals, shared system
  prompts, per-request TTFT/e2e deadlines) through the paged engine
  (block KV cache + prefix reuse + chunked prefill, optionally
  ``--draft N`` speculative decoding), A/B'd against the v1 engine on
  the same trace.  The record carries ``prefix_hit_rate``,
  ``slo_attainment``, ``spec_acceptance`` and the prefill-FLOPs saving.

    JAX_PLATFORMS=cpu python scripts/serve_bench.py              # v1 A/B
    python scripts/serve_bench.py --paged                        # paged
    python scripts/serve_bench.py --paged --draft 1 --spec-k 4 \
        --kv-block-size 16 --prefill-chunk 32 --slo-ttft-ms 500  # full
    python scripts/serve_bench.py --paged \
        --kv-dtype int8 --weight-dtype int8            # quantized path

Defaults are CPU-CI sized; see PERFORMANCE.md §Serving for recorded
numbers and the knob trade-offs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _script_env() -> None:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _latency_line(tag: str, lat: dict) -> None:
    if not lat.get("measured_requests"):
        return
    print(f"{tag} latency over {lat['measured_requests']} requests: "
          f"ttft p50={lat['ttft_p50_s'] * 1e3:.1f}ms "
          f"p99={lat['ttft_p99_s'] * 1e3:.1f}ms | "
          f"itl p50={lat['itl_p50_s'] * 1e3:.2f}ms "
          f"p99={lat['itl_p99_s'] * 1e3:.2f}ms | "
          f"e2e p50={lat['e2e_p50_s']:.3f}s "
          f"p99={lat['e2e_p99_s']:.3f}s",
          file=sys.stderr)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="serving bench: continuous-batching / paged engine "
                    "vs baselines")
    p.add_argument("--requests", type=int, default=None,
                   help="trace size (default: 32 v1 / 24 paged)")
    p.add_argument("--max-slots", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    # --- trace shape (both modes; paged splits the prompt envelope
    #     into short/long halves around its midpoint) ---
    p.add_argument("--prompt-min", type=int, default=None,
                   help="prompt length lower bound (default 4)")
    p.add_argument("--prompt-max", type=int, default=None,
                   help="prompt length upper bound (default 48)")
    p.add_argument("--new-min", type=int, default=None,
                   help="decode length lower bound (default 4)")
    p.add_argument("--new-max", type=int, default=None,
                   help="decode length upper bound (default 64)")
    p.add_argument("--stagger", type=int, default=0,
                   help="v1 trace: mean inter-arrival gap in decode "
                        "ticks (0 = all requests queued up front)")
    p.add_argument("--buckets", type=str, default=None,
                   help="v1 engine: comma-separated prefill bucket "
                        "lengths (default: powers of two up to max-len)")
    p.add_argument("--skip-naive", action="store_true",
                   help="v1 mode: engine only (e.g. profiling)")
    # --- paged mode ---
    p.add_argument("--paged", action="store_true",
                   help="bench the paged engine under trace-driven "
                        "SLO load instead of the v1 A/B")
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--prefill-chunk", type=int, default=32)
    p.add_argument("--draft", type=int, default=0,
                   help="speculative decoding: draft layer count "
                        "(0 = off; draft shares the target's weights)")
    p.add_argument("--spec-k", type=int, default=4)
    p.add_argument("--arrival", choices=("front", "poisson", "bursty"),
                   default=None, help="paged trace arrival process")
    p.add_argument("--rate", type=float, default=None,
                   help="paged trace: mean arrivals per decode tick")
    p.add_argument("--shared-prefix-len", type=int, default=None,
                   help="paged trace: shared system-prompt length")
    p.add_argument("--shared-frac", type=float, default=None,
                   help="paged trace: fraction of requests opening "
                        "with the shared prefix")
    p.add_argument("--slo-ttft-ms", type=float, default=None)
    p.add_argument("--slo-e2e-ms", type=float, default=None)
    p.add_argument("--skip-v1", action="store_true",
                   help="paged mode: skip the v1-engine comparison leg")
    # --- serving quantization (both modes; int8 KV is paged-only) ---
    p.add_argument("--kv-dtype", default=None,
                   help="KV-cache storage dtype: bf16 or int8 (int8 "
                        "stores per-position scales in the block pools, "
                        "so it requires --paged; unset = full precision)")
    p.add_argument("--weight-dtype", default=None,
                   help="decode weight storage dtype: bf16 or int8 "
                        "(int8 = per-channel scales, dequantized inside "
                        "the compiled decode program; unset = full "
                        "precision)")
    # model geometry (default: CPU-CI-sized, serve/bench.py)
    p.add_argument("--layers", type=int, default=None)
    p.add_argument("--d-model", type=int, default=None)
    p.add_argument("--heads", type=int, default=None)
    p.add_argument("--mlp-dim", type=int, default=None)
    p.add_argument("--vocab", type=int, default=None)
    p.add_argument("--max-len", type=int, default=None)
    p.add_argument("--out", default=None, help="also write the JSON here")
    # --- observability (ISSUE 11) ---
    p.add_argument("--obs", action="store_true",
                   help="attach run telemetry: JSONL event stream "
                        "(--obs-file) + per-request span trace exported "
                        "as Chrome/Perfetto JSON (--obs-trace)")
    p.add_argument("--obs-file", default="obs_events.jsonl",
                   help="telemetry event stream path (with --obs)")
    p.add_argument("--obs-trace", default=None, metavar="PATH",
                   help="span-trace output path (default "
                        "serve_trace.json with --obs; giving a path "
                        "implies --obs)")
    args = p.parse_args(argv)

    # parse-time quantization legality: fail HERE with the flag name,
    # not minutes later inside an engine constructor
    from distributed_deep_learning_tpu.serve.quant import SERVE_DTYPES

    for flag, val in (("--kv-dtype", args.kv_dtype),
                      ("--weight-dtype", args.weight_dtype)):
        if val is not None and val not in SERVE_DTYPES:
            p.error(f"unknown {flag} {val!r}; choose from "
                    f"{'/'.join(SERVE_DTYPES)} (or leave unset for "
                    "full precision)")
    if args.kv_dtype == "int8" and not args.paged:
        p.error("--kv-dtype int8 requires --paged: int8 KV stores "
                "per-position scales alongside the block pools; the v1 "
                "slot table supports bf16 only (the spec-decode draft "
                "pool inherits --kv-dtype automatically)")

    telemetry = None
    if args.obs or args.obs_trace:
        from distributed_deep_learning_tpu.obs import RunTelemetry

        telemetry = RunTelemetry(
            path=args.obs_file,
            trace_path=args.obs_trace or "serve_trace.json")

    model_kw = {k: v for k, v in (
        ("num_layers", args.layers), ("d_model", args.d_model),
        ("num_heads", args.heads), ("mlp_dim", args.mlp_dim),
        ("vocab_size", args.vocab), ("max_len", args.max_len),
    ) if v is not None}

    if args.paged:
        from distributed_deep_learning_tpu.serve.bench import \
            paged_serving_bench

        load_kw = {k: v for k, v in (
            ("n_requests", args.requests), ("arrival", args.arrival),
            ("rate", args.rate),
            ("shared_prefix_len", args.shared_prefix_len),
            ("shared_frac", args.shared_frac),
            ("slo_ttft_ms", args.slo_ttft_ms),
            ("slo_e2e_ms", args.slo_e2e_ms),
        ) if v is not None}
        if args.prompt_min is not None or args.prompt_max is not None:
            lo = 4 if args.prompt_min is None else args.prompt_min
            hi = 48 if args.prompt_max is None else args.prompt_max
            if hi <= lo:
                p.error(f"--prompt-max must exceed --prompt-min "
                        f"(got {lo}..{hi})")
            mid = max(lo + 1, (lo + hi) // 2)
            load_kw["prompt_short"] = (lo, mid)
            load_kw["prompt_long"] = (mid, hi)
        if args.new_min is not None or args.new_max is not None:
            lo = 4 if args.new_min is None else args.new_min
            hi = 64 if args.new_max is None else args.new_max
            if hi <= lo:
                p.error(f"--new-max must exceed --new-min "
                        f"(got {lo}..{hi})")
            load_kw["new_tokens"] = (lo, hi)
        try:
            record = paged_serving_bench(
                seed=args.seed, load_kw=load_kw, model_kw=model_kw,
                max_slots=args.max_slots,
                kv_block_size=args.kv_block_size,
                prefill_chunk=args.prefill_chunk,
                draft_layers=args.draft or None, spec_k=args.spec_k,
                compare_engine=not args.skip_v1,
                kv_dtype=args.kv_dtype, weight_dtype=args.weight_dtype,
                telemetry=telemetry)
        except ValueError as e:
            p.error(f"{e} — shrink the trace (--prompt-max / --new-max "
                    f"/ --shared-prefix-len) or raise --max-len")
        pe = record["paged_engine"]
        _latency_line("paged", pe.get("latency") or {})
        print(f"prefix_hit_rate={pe['prefix_hit_rate']:.3f} "
              f"slo_attainment={pe['slo_attainment']} "
              f"spec_acceptance={pe['spec_acceptance']}",
              file=sys.stderr)
    else:
        from distributed_deep_learning_tpu.serve.bench import serving_bench

        buckets = [int(b) for b in args.buckets.split(",")] \
            if args.buckets else None
        record = serving_bench(
            seed=args.seed, n_requests=args.requests or 32,
            model_kw=model_kw,
            prompt_lens=(4 if args.prompt_min is None else args.prompt_min,
                         48 if args.prompt_max is None else args.prompt_max),
            new_tokens=(4 if args.new_min is None else args.new_min,
                        64 if args.new_max is None else args.new_max),
            max_slots=args.max_slots, prefill_buckets=buckets,
            stagger=args.stagger, skip_naive=args.skip_naive,
            kv_dtype=args.kv_dtype, weight_dtype=args.weight_dtype,
            telemetry=telemetry)
        _latency_line("engine", record["engine"].get("latency") or {})

    if telemetry is not None:
        summary = telemetry.close()
        tr = summary.get("trace")
        if tr:
            print(f"obs: {tr['spans']} spans -> {tr['path']} "
                  f"(load in Perfetto / chrome://tracing); "
                  f"events -> {args.obs_file}", file=sys.stderr)

    out = json.dumps(record)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    _script_env()
    sys.exit(main())
