"""The planner's driver: enumerate → prune → rank → successive halving.

Pipeline: :func:`~.space.enumerate_plans` builds the legal lattice;
:func:`~.memory.prune_plans` drops analytically infeasible points before
any compile; survivors are ranked by a deterministic analytic cost score
(recompute/traffic multipliers — the pre-compile stand-in for XLA's
``cost_analysis``, which each trial records once it HAS compiled) and
capped to ``max_trials`` (the dropped count is logged — a silent cap would
read as full coverage); then successive halving measures the pool with
:class:`~.trial.TrialHarness`, keeping the top ``1/eta`` by steps/sec and
doubling the measured steps per rung.

The hand-default config's own plan rides through every rung, so the final
rung always contains an apples-to-apples baseline measurement and the
winner is ≥ it by construction of the argmax.  The whole search is
deterministic under a seed: enumeration order is fixed, ties break on
``plan_hash``, and wall-clock fields are excluded from the deterministic
record (:meth:`SearchResult.record`).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Sequence

import jax
import numpy as np

from distributed_deep_learning_tpu.tune.artifact import plan_hash
from distributed_deep_learning_tpu.tune.memory import (ModelGeometry,
                                                       OPT_SLOTS,
                                                       estimate_memory,
                                                       hbm_budget,
                                                       prune_plans)
from distributed_deep_learning_tpu.tune.space import (Plan, enumerate_plans,
                                                      plan_from_config)
from distributed_deep_learning_tpu.tune.trial import TrialHarness, TrialResult
from distributed_deep_learning_tpu.utils.config import Config

#: analytic step-cost multiplier per (remat, policy): remat trades FLOPs
#: for memory, so heavier recompute ranks later when the trial pool is
#: capped (the measured rungs have the final word)
RECOMPUTE_COST = {
    (False, "nothing"): 1.00,
    (True, "dots"): 1.15,
    (True, "dots_no_batch"): 1.25,
    (True, "nothing"): 1.35,
}

#: transformer-family workloads — their activation geometry scales with
#: sequence length, not feature count
_SEQ_WORKLOADS = ("gpt", "bert", "transformer", "moe", "lstm")


def analytic_score(plan: Plan, recompute_cost: dict[tuple[bool, str], float]
                   | None = None) -> float:
    """Lower = expected faster; a coarse pre-compile ranking only.

    ``recompute_cost`` optionally replaces the static table with a
    calibration's measured per-corner step-cost ratios; corners it
    doesn't cover keep the analytic value."""
    key = (plan.remat, plan.remat_policy)
    if recompute_cost is not None and key in recompute_cost:
        score = float(recompute_cost[key])
    else:
        score = RECOMPUTE_COST[key]
    score *= 1.0 + 0.05 * (plan.grad_accum - 1)   # scan overhead
    if plan.zero == "1":
        score *= 1.05                             # moment allgather
    elif plan.zero == "fsdp":
        score *= 1.10                             # param+moment allgather
    if plan.grad_compress != "none":
        score *= 1.02                             # quantize/dequantize work
    if plan.comm != "none":
        score *= 1.02                             # quantize/dequantize work
        if plan.comm_overlap:
            score *= 0.99                         # ring hides wire time
    return score


def model_geometry(spec, config: Config, dataset) -> ModelGeometry:
    """Analytic geometry for the memory model.  The parameter count comes
    from ``jax.eval_shape`` over the real ``model.init`` — exact and free
    (no arrays are materialised); activation terms are per-family
    formulas, good to ordering (the trials cross-check bytes)."""
    model = spec.build_model(config, dataset)
    example = spec.example_input(config, dataset)
    shapes = jax.eval_shape(model.init, jax.random.key(0), example)
    param_count = sum(int(np.prod(l.shape))
                     for l in jax.tree.leaves(dict(shapes).get("params", {})))
    x, _ = dataset.batch(np.arange(1))
    width = max(1, config.size)
    if spec.name in _SEQ_WORKLOADS and np.ndim(x) > 1:
        seq = int(np.shape(x)[1])
        # attention scores + MLP intermediates dominate: ~8 x d_model
        # elems per token per layer
        layer_act = seq * width * 8
        extra = seq * width * 2                   # embeddings + head staging
    else:
        layer_act = width * 4                     # dense + norm + nonlin
        extra = int(np.prod(np.shape(x)[1:]))     # input staging
    return ModelGeometry(param_count=param_count,
                         num_layers=max(1, config.num_layers),
                         layer_act_elems_per_example=layer_act,
                         extra_act_elems_per_example=extra,
                         opt_slots=OPT_SLOTS.get(config.optimizer, 2))


@dataclasses.dataclass
class SearchResult:
    best: Plan
    best_sps: float
    baseline: Plan
    baseline_sps: float
    n_devices: int
    n_candidates: int
    n_pruned: int
    n_capped: int
    n_infeasible: int
    rungs: int
    budget_bytes: int | None
    trials: list[TrialResult]
    search_seconds: float

    def record(self, *, deterministic_only: bool = False) -> dict[str, Any]:
        """JSON-able summary.  ``deterministic_only`` keeps exactly the
        fields that must be bit-identical across seeded runs (no wall
        clocks, no backend-dependent analyses)."""
        d = {
            "best_plan": self.best.to_dict(),
            "best_plan_hash": plan_hash(self.best),
            "best_steps_per_sec": self.best_sps,
            "baseline_plan": self.baseline.to_dict(),
            "baseline_plan_hash": plan_hash(self.baseline),
            "baseline_steps_per_sec": self.baseline_sps,
            "n_devices": self.n_devices,
            "n_candidates": self.n_candidates,
            "n_pruned_analytic": self.n_pruned,
            "n_capped": self.n_capped,
            "n_infeasible": self.n_infeasible,
            "rungs": self.rungs,
            "trials": [t.to_dict(deterministic_only=deterministic_only)
                       for t in self.trials],
        }
        if not deterministic_only:
            d["budget_bytes"] = self.budget_bytes
            d["search_seconds"] = self.search_seconds
        return d


def run_search(spec, config: Config, *, devices=None, dataset=None,
               logger=None, trial_steps: int = 4, warmup: int = 2,
               eta: int = 2, max_trials: int | None = 16,
               max_rungs: int = 6, budget_bytes: int | None = None,
               measure: Callable[[Plan, int], float] | None = None,
               oom_hook: Callable[[Plan], None] | None = None,
               space_options: dict[str, Sequence] | None = None,
               calibration=None,
               ) -> SearchResult:
    """Search the plan lattice for `spec` under `config`'s geometry.

    ``space_options`` forwards to :func:`~.space.enumerate_plans` (restrict
    dtypes / zero / compress / accumulation for cheap searches);
    ``max_trials=None`` lifts the pool cap.  ``measure`` / ``oom_hook``
    are the deterministic / chaos injection points (see
    :class:`~.trial.TrialHarness`).  ``calibration`` is an optional
    :class:`~.calibrate.MemoryCalibration`: its measured ``act_fraction``
    constants replace the analytic table in pruning and memory-ranked
    ordering, its ``recompute_cost`` the static step-cost multipliers in
    the analytic score — corners a calibration doesn't cover fall back to
    the tables per-corner."""
    t_start = time.perf_counter()
    if devices is None:
        from distributed_deep_learning_tpu.workloads.base import _devices

        devices = _devices(config)
    devices = list(devices)
    n = len(devices)
    if dataset is None:
        dataset = spec.build_dataset(config)
    opts = dict(space_options or {})
    opts.setdefault("dtypes", (config.dtype,))
    plans = enumerate_plans(n, config.batch_size, **opts)
    geom = model_geometry(spec, config, dataset)
    budget = hbm_budget(devices, override=budget_bytes)
    act_fraction = getattr(calibration, "act_fraction", None)
    recompute_cost = getattr(calibration, "recompute_cost", None)
    feasible, rejected = prune_plans(plans, geom, config.batch_size, budget,
                                     act_fraction=act_fraction)
    if not feasible:
        raise ValueError(
            f"memory model pruned all {len(plans)} candidate plans "
            f"(budget {budget} bytes); nothing to measure")

    order = sorted(feasible, key=lambda p: (
        analytic_score(p, recompute_cost),
        estimate_memory(p, geom, config.batch_size,
                        act_fraction=act_fraction).total_bytes,
        plan_hash(p)))
    n_capped = 0
    if max_trials is not None and len(order) > max_trials:
        n_capped = len(order) - max_trials
        order = order[:max_trials]
        if logger:
            logger.info(f"autotune: trial pool capped at {max_trials} of "
                        f"{len(feasible)} feasible plans ({n_capped} "
                        "dropped by analytic rank)")
    baseline = plan_from_config(config, n)
    if baseline not in order:
        order = order + [baseline]

    harness = TrialHarness(spec, config, dataset, devices, warmup=warmup,
                           oom_hook=oom_hook, measure=measure)
    trials: list[TrialResult] = []
    survivors = order
    steps = trial_steps
    rungs = 0
    while True:
        rung = [harness.run(p, steps) for p in survivors]
        trials.extend(rung)
        rungs += 1
        alive = sorted((r for r in rung if not r.infeasible),
                       key=lambda r: (-r.steps_per_sec, plan_hash(r.plan)))
        if not alive:
            raise RuntimeError(
                "no plan survived measured trials (every candidate "
                "infeasible) — see the trial errors in the search record")
        if len(alive) <= 2 or rungs >= max_rungs:
            final = alive
            break
        keep = max(2, len(alive) // eta)
        nxt = [r.plan for r in alive[:keep]]
        if baseline not in nxt:
            # the hand default rides every rung: the final comparison must
            # be measured in the same rung as the winner
            nxt.append(baseline)
        if set(nxt) == {r.plan for r in rung}:
            final = alive   # halving reached a fixpoint
            break
        if logger:
            logger.info(f"autotune rung {rungs}: {len(alive)} alive, "
                        f"keeping {len(nxt)}; next rung {steps * 2} steps")
        survivors = nxt
        steps *= 2

    best = final[0]
    baseline_result = next((r for r in final if r.plan == baseline), None)
    if baseline_result is None:   # baseline went infeasible mid-search
        baseline_result = next(
            (r for r in reversed(trials)
             if r.plan == baseline and not r.infeasible), None)
    baseline_sps = baseline_result.steps_per_sec if baseline_result else 0.0
    return SearchResult(
        best=best.plan, best_sps=best.steps_per_sec,
        baseline=baseline, baseline_sps=baseline_sps,
        n_devices=n, n_candidates=len(plans), n_pruned=len(rejected),
        n_capped=n_capped,
        n_infeasible=sum(1 for r in trials if r.infeasible),
        rungs=rungs, budget_bytes=budget, trials=trials,
        search_seconds=time.perf_counter() - t_start)
