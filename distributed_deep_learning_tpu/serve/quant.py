"""Serving-path quantization: int8 weights and a quantized KV cache.

Decode is memory-bound: at generation time every token streams the whole
parameter set and the slot's entire KV history through the MXU for a few
FLOPs each, so HBM bytes — not compute — cap slots, context length and
prefix-cache size per chip.  This module cuts those bytes without
inventing new numerics: the symmetric int8 machinery is
:func:`..parallel.collectives.quantize` / ``dequantize`` — the same
common-scale wire format the ZeRO/FSDP comm layer ships — applied at two
granularities chosen for the serving data layout:

* **Weights** (:func:`quantize_weights`) — per-OUTPUT-CHANNEL scales
  (one ``collectives.quantize`` per last-axis column, vmapped): matmul
  kernels have output features on the last axis, so each channel gets
  its own amax and the dequant ``q * s`` broadcasts along exactly that
  axis.  Only ``ndim >= 2`` floating leaves quantize; biases and norm
  scales are O(d) bytes and precision-critical, so they stay put.
  Dequantization happens INSIDE the jitted decode program
  (:func:`dequantize_weights` at the top of each impl), so XLA fuses
  the ``int8 -> f32 * scale`` upcast into the matmul operand and no
  full-precision weight copy ever exists at rest.
* **KV cache** (:func:`quantize_kv`) — per-POSITION-per-HEAD scales
  (one ``collectives.quantize`` per ``(..., D)`` row): a decode tick
  writes ONE new position into a block that already holds committed
  positions, so any coarser grain (per-block scales) would need a
  read-modify-write rescale of frozen neighbours — breaking both the
  compile-once scatter and prefix-block immutability (a COW-shared
  block's bytes must never change under its chain hash).  Row scales
  make every position self-contained: blocks stay bit-frozen once
  committed, so :class:`.paged.BlockManager` reuse, copy-on-write and
  the supervisor's replay ledger operate on the quantized
  representation unchanged.

The quantized KV pool is a tree of :class:`QuantTensor` — a registered
pytree node holding the int8 payload ``q`` and its f32 scales ``s``
with IDENTICAL leading dims (``s`` is ``q.shape[:-1] + (1,)``).  That
shape choice is the whole trick: every existing pool op in
:mod:`.paged` (``gather_slot``'s ``leaf[table]``, ``scatter_span``'s
``.at[blocks, offsets]``, ``copy_block``'s block slice) indexes leading
axes only, so ``jax.tree.map`` descending into ``q`` and ``s`` applies
each op to both arrays correctly with ZERO changes to the op — and
``obs.memory.pytree_bytes`` counts payload + scales automatically.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from distributed_deep_learning_tpu.parallel.collectives import (dequantize,
                                                                quantize)
from distributed_deep_learning_tpu.serve.cache import KV_LEAVES, _leaf_name

#: reduced-precision storage formats the serving CLI accepts for
#: ``--kv-dtype`` / ``--weight-dtype`` (``None``/unset means full
#: precision — the engine default, which keeps every exact-parity
#: guarantee bit-identical)
SERVE_DTYPES = ("bf16", "int8")


@jax.tree_util.register_pytree_with_keys_class
@dataclasses.dataclass
class QuantTensor:
    """int8 payload + f32 scales travelling as ONE pytree node.

    ``s`` has ``q``'s leading dims (``q.shape[:-1] + (1,)`` for KV rows,
    ``(C,)`` for weight channels), so tree-mapped indexing ops hit both
    arrays coherently.  A registered class — not a raw ``{"q","s"}``
    dict — because param trees contain modules literally named ``q``;
    ``isinstance`` (via :func:`is_quant`) is the only safe detector.
    """

    q: jax.Array
    s: jax.Array

    def tree_flatten_with_keys(self):
        return ((jax.tree_util.GetAttrKey("q"), self.q),
                (jax.tree_util.GetAttrKey("s"), self.s)), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype


def is_quant(x) -> bool:
    return isinstance(x, QuantTensor)


def check_dtype(name: str, value):
    """Validate a ``--kv-dtype`` / ``--weight-dtype`` value (``None``
    passes — full precision).  Shared by the CLI parsers and the engine
    constructors so both reject with the same message."""
    if value is not None and value not in SERVE_DTYPES:
        raise ValueError(f"unknown {name} {value!r}; "
                         f"choose from {SERVE_DTYPES} (or leave unset "
                         "for full precision)")
    return value


# --------------------------------------------------------------------------
# leaf-level quantizers (vmapped reuse of the collectives wire format)
# --------------------------------------------------------------------------


def quantize_channels(x) -> QuantTensor:
    """Per-last-axis-channel symmetric int8: one
    :func:`collectives.quantize` per output-feature column."""
    flat = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    q, s = jax.vmap(lambda col: quantize(col, "int8"),
                    in_axes=1, out_axes=(1, 0))(flat)
    return QuantTensor(q.reshape(x.shape), s)


def quantize_rows(x) -> QuantTensor:
    """Per-row symmetric int8 (every leading index gets its own scale
    over the last axis): one :func:`collectives.quantize` per
    position-per-head KV vector."""
    flat = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    q, s = jax.vmap(lambda row: quantize(row, "int8"))(flat)
    return QuantTensor(q.reshape(x.shape),
                       s.reshape(x.shape[:-1] + (1,)))


def dequant(qt: QuantTensor, dtype):
    """``q * s`` via :func:`collectives.dequantize` (f32 accumulate),
    cast to the engine's compute dtype."""
    return dequantize(qt.q, qt.s, "int8", jnp.float32).astype(dtype)


# --------------------------------------------------------------------------
# weights
# --------------------------------------------------------------------------


def quantize_weights(params, weight_dtype: str):
    """Reduced-precision AT-REST form of a decode param tree.

    ``int8``: per-channel :class:`QuantTensor` for every ``ndim >= 2``
    floating leaf (matmul kernels + embed table); vectors (biases, norm
    scales) stay full precision.  ``bf16``: a plain cast — the cast IS
    the quantization, no scales needed.
    """
    check_dtype("weight_dtype", weight_dtype)

    def wq(leaf):
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        if weight_dtype == "bf16":
            return leaf.astype(jnp.bfloat16)
        return quantize_channels(leaf) if leaf.ndim >= 2 else leaf

    return jax.tree.map(wq, params)


def dequantize_weights(params, dtype):
    """Compute-dtype view of an at-rest param tree — called at the TOP
    of each jitted impl, so the upcast fuses into each consumer matmul
    and no full-precision copy survives between programs."""
    def wd(leaf):
        if is_quant(leaf):
            return dequant(leaf, dtype)
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    return jax.tree.map(wd, params, is_leaf=is_quant)


def weight_bytes(params) -> int:
    """At-rest bytes of a (possibly quantized) param tree — payload plus
    scales, same accounting as ``obs.memory.pytree_bytes``."""
    return int(sum(leaf.size * leaf.dtype.itemsize
                   for leaf in jax.tree.leaves(params)))


# --------------------------------------------------------------------------
# KV cache
# --------------------------------------------------------------------------


def quantize_kv(x, kv_dtype: str):
    """One KV leaf → its at-rest form (per-row int8 or a bf16 cast)."""
    if kv_dtype == "bf16":
        return x.astype(jnp.bfloat16)
    if kv_dtype == "int8":
        return quantize_rows(x)
    raise ValueError(f"unknown kv_dtype {kv_dtype!r}; "
                     f"choose from {SERVE_DTYPES}")


def _is_kv(path, leaf) -> bool:
    if is_quant(leaf):
        return True
    return (_leaf_name(path) in KV_LEAVES
            and jnp.issubdtype(leaf.dtype, jnp.floating))


def quantize_cache_span(span, kv_dtype: str):
    """Freshly-computed (floating) KV positions → the pool's at-rest
    representation, ready for ``scatter_span``/``write_slot``.
    Counters and the bool validity mask pass through exact."""
    def f(path, leaf):
        return quantize_kv(leaf, kv_dtype) if _is_kv(path, leaf) else leaf

    return jax.tree_util.tree_map_with_path(f, span)


def dequant_cache(cache, dtype):
    """At-rest cache/pool tree → the model's floating layout at the
    engine's compute dtype (the model's ``dynamic_update_slice`` cache
    writes are dtype-strict, so gathered KV must match computed K/V)."""
    def f(path, leaf):
        if is_quant(leaf):
            return dequant(leaf, dtype)
        if _is_kv(path, leaf):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(f, cache, is_leaf=is_quant)


def cast_kv(cache, dtype):
    """Cast the floating KV leaves of a NON-int8 cache tree (used by the
    v1 engine's bf16 path, where the cast is the whole transform)."""
    def f(path, leaf):
        if _leaf_name(path) in KV_LEAVES and \
                jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(f, cache)


# --------------------------------------------------------------------------
# calibration
# --------------------------------------------------------------------------


def calibrate_weight_drift(model, params, qparams, probe_tokens, *,
                           margin: float = 1.5, floor: float = 5e-3):
    """Measure what int8 weights do to the greedy path on a probe batch
    and DECLARE the per-token logprob-drift bound the parity gate will
    hold the engine to.

    Runs the full (non-decode) forward under the original and the
    dequantized params, compares ``log_softmax`` at each position's
    full-precision argmax token (the greedy trajectory — the quantity
    the drift-bounded parity tests measure), and returns
    ``max(margin * max_drift, floor)`` so the declared bound has real
    headroom over the measured worst case without being vacuous.
    """
    full = model.clone(decode=False, with_logits=True)
    toks = jnp.asarray(probe_tokens)
    if toks.ndim == 1:
        toks = toks[None]

    compute = jax.tree.leaves(params)[0].dtype
    ref = full.apply({"params": params}, toks)
    deq = full.apply({"params": dequantize_weights(qparams, compute)},
                     toks)
    ref_lp = jax.nn.log_softmax(ref.astype(jnp.float32), axis=-1)
    deq_lp = jax.nn.log_softmax(deq.astype(jnp.float32), axis=-1)
    pick = jnp.argmax(ref_lp, axis=-1)[..., None]
    drift = jnp.abs(jnp.take_along_axis(ref_lp, pick, axis=-1)
                    - jnp.take_along_axis(deq_lp, pick, axis=-1))
    measured = float(jnp.max(drift))
    agree = float(jnp.mean(jnp.argmax(deq_lp, axis=-1)
                           == pick[..., 0]))
    return {
        "measured_max_drift": measured,
        "declared_bound": max(margin * measured, floor),
        "probe_argmax_agreement": agree,
        "probe_tokens": int(toks.size),
    }
