"""The WMT seq2seq workload (BASELINE.json configs[3]) under sequence
parallelism on PADDED batches — the composition VERDICT r4 item 4 flagged:
ring/Ulysses must serve the framework's own flagship seq model with real
variable-length data (synthetic_wmt pads rows to src/tgt_len with 0s).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_deep_learning_tpu.data.datasets import synthetic_wmt
from distributed_deep_learning_tpu.models.transformer import (
    TransformerSeq2Seq)
from distributed_deep_learning_tpu.parallel import ring_attention, ulysses
from distributed_deep_learning_tpu.runtime.mesh import build_mesh


@pytest.fixture(scope="module")
def mesh_seq8():
    return build_mesh({"seq": 8})


@pytest.fixture(scope="module")
def wmt_batch():
    ds = synthetic_wmt(n=4, src_len=32, tgt_len=32, vocab_size=64, seed=3)
    batch = {"inputs": jnp.asarray(ds.features),
             "targets": jnp.asarray(ds.targets)}
    assert (np.asarray(ds.features) == 0).any(), "fixture must be padded"
    return batch


def _model(attention_fn=None):
    return TransformerSeq2Seq(vocab_size=64, num_layers=2, d_model=32,
                              num_heads=8, mlp_dim=64, dropout_rate=0.0,
                              attention_fn=attention_fn)


def _loss(model, params, batch):
    """Mean CE over non-pad target positions (the padded-loss convention)."""
    logits = model.apply(params, batch)
    valid = (batch["targets"] != 0).astype(jnp.float32)
    ll = jax.nn.log_softmax(logits)
    ce = -jnp.take_along_axis(ll, batch["targets"][..., None],
                              axis=-1)[..., 0]
    return jnp.sum(ce * valid) / jnp.sum(valid)


@pytest.mark.parametrize("scheme", ["ring", "ulysses"])
def test_wmt_padded_forward_parity(mesh_seq8, wmt_batch, scheme):
    """Same params, dense vs sequence-parallel attention: logits match on
    the padded WMT batch (enc self / dec causal self / cross, all with
    key_valid threading through the seq axis)."""
    adapter = (ring_attention if scheme == "ring" else ulysses) \
        .make_attention_fn(mesh_seq8)
    dense = _model()
    sp = _model(attention_fn=adapter)
    params = dense.init(jax.random.key(0), wmt_batch)
    expected = dense.apply(params, wmt_batch)
    with mesh_seq8:
        got = jax.jit(lambda p, b: sp.apply(p, b))(params, wmt_batch)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=5e-4, atol=5e-4)


def test_wmt_padded_train_step_parity(mesh_seq8, wmt_batch):
    """One padded-loss gradient step under ring SP matches dense."""
    dense = _model()
    sp = _model(attention_fn=ring_attention.make_attention_fn(mesh_seq8))
    params = dense.init(jax.random.key(0), wmt_batch)

    ld, gd = jax.value_and_grad(lambda p: _loss(dense, p, wmt_batch))(params)
    with mesh_seq8:
        ls, gs = jax.jit(jax.value_and_grad(
            lambda p: _loss(sp, p, wmt_batch)))(params)
    np.testing.assert_allclose(float(ls), float(ld), rtol=1e-4)
    flat_d = jax.tree_util.tree_leaves(gd)
    flat_s = jax.tree_util.tree_leaves(gs)
    for a, b in zip(flat_s, flat_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)
