"""Test env: emulate an 8-device host platform before JAX initialises.

The JAX analogue of the reference's fake CPU device-list trick
(``LSTM/model.py:183`` builds a model over ``devices=[cpu]*4``): with
``--xla_force_host_platform_device_count=8`` every pjit/shard_map/collective
path runs for real on one machine (SURVEY.md §4).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env may pin a TPU platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A site-installed TPU plugin may override the platform via jax.config at
# interpreter startup; force it back to CPU before any backend initialises.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running integration tests")
    config.addinivalue_line(
        "markers", "smoke: fast pre-snapshot tier (~4 min on a 2-core box)")


#: The fast smoke tier (VERDICT r4 weak 7: the full suite outgrew any
#: deadline — 422 not-slow tests ≈ 29 min on a loaded 2-core box — so a
#: red HEAD needs a gate that actually gets run).  One fast representative
#: file per subsystem, ~250 s of measured test time total; run with
#:     python -m pytest tests/ -m smoke -q
#: The marker is applied per-FILE here so the curated set lives in one
#: place; slow-marked tests stay excluded even inside smoke files.
SMOKE_FILES = {
    "test_config.py", "test_data.py", "test_native.py", "test_mesh.py",
    "test_partition.py", "test_determinism.py", "test_train_mlp.py",
    "test_checkpoint.py", "test_step_checkpoint.py", "test_elastic.py",
    "test_spmd_pipeline.py", "test_mpmd.py", "test_zero.py",
    "test_tensor_parallel.py", "test_ulysses.py", "test_fused_ce.py",
    "test_profiling.py", "test_schedules.py", "test_compress.py",
    "test_host_pipeline.py", "test_attention_pallas.py",
    "test_torch_migrate.py", "test_chaos.py", "test_tune.py",
    "test_reshard.py", "test_obs.py", "test_collectives.py",
}


def pytest_collection_modifyitems(config, items):
    import os as _os

    for item in items:
        if _os.path.basename(str(item.fspath)) in SMOKE_FILES \
                and item.get_closest_marker("slow") is None:
            item.add_marker(pytest.mark.smoke)


@pytest.fixture(scope="session")
def mesh8():
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    return build_mesh({"data": 8})


@pytest.fixture(scope="session")
def mesh_4x2():
    from distributed_deep_learning_tpu.runtime.mesh import build_mesh
    return build_mesh({"data": 4, "stage": 2})


def padded_valid(T=32, lengths=(20, 32)):
    """(len(lengths), T) bool key_valid with ragged True prefixes — the
    shared padded-batch fixture for the SP/flash parity suites."""
    import jax.numpy as jnp

    return jnp.arange(T)[None, :] < jnp.array(lengths)[:, None]
