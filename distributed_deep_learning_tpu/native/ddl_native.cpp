// Native host-side data kernels for the TPU framework's input pipeline.
//
// The reference delegates its data hot path to native code it doesn't own:
// pandas' C CSV engine, PIL/torchvision image ops, and libxml2
// (reference CNN/dataset.py:32-40,99-107; MLP/dataset.py:28).  This library
// is the first-party equivalent for the operations that sit on the
// per-step critical path of the host loader:
//
//   * ddl_gather_rows      — batched row gather (ArrayDataset.batch)
//   * ddl_window_gather    — sliding-window gather (PdM LSTM windows)
//   * ddl_csv_dims/parse   — float CSV reader (MQTT / PdM datasets)
//   * ddl_crop_resize_bilinear — bbox crop + bilinear resize (PCB images)
//
// All entry points use a C ABI (loaded via ctypes; no pybind11 in the
// image) and operate on caller-allocated buffers so NumPy owns all memory.
// Parallelism: std::thread over contiguous output chunks — every routine
// is embarrassingly parallel over rows.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

int64_t worker_count(int64_t items, int64_t min_per_thread) {
  int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 4;
  int64_t by_items = items / min_per_thread;
  return std::max<int64_t>(1, std::min(hw, by_items));
}

// Run fn(begin, end) over [0, n) in parallel chunks.
template <typename Fn>
void parallel_for(int64_t n, int64_t min_per_thread, Fn fn) {
  int64_t workers = worker_count(n, min_per_thread);
  if (workers <= 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + workers - 1) / workers;
  for (int64_t w = 0; w < workers; ++w) {
    int64_t begin = w * chunk;
    int64_t end = std::min(n, begin + chunk);
    if (begin >= end) break;
    threads.emplace_back([=] { fn(begin, end); });
  }
  for (auto& t : threads) t.join();
}

}  // namespace

extern "C" {

// out[i, :] = data[idx[i], :]; data is (n_rows, d) row-major.
void ddl_gather_rows(const float* data, int64_t d, const int64_t* idx,
                     int64_t b, float* out) {
  parallel_for(b, 1024, [=](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      std::memcpy(out + i * d, data + idx[i] * d, sizeof(float) * d);
    }
  });
}

// out[i] = data[pos[i]-history+1 .. pos[i]+1, :]  →  (b, history, d).
// pos[i] is the window END row (the reference's idx2pos convention,
// LSTM/dataset.py:36-39).
void ddl_window_gather(const float* data, int64_t d, const int64_t* pos,
                       int64_t b, int64_t history, float* out) {
  const int64_t window = history * d;
  parallel_for(b, 256, [=](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      const float* src = data + (pos[i] - history + 1) * d;
      std::memcpy(out + i * window, src, sizeof(float) * window);
    }
  });
}

namespace {

// Read a whole file; returns empty on failure.
std::string read_file(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return {};
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  size_t got = std::fread(buf.data(), 1, buf.size(), f);
  std::fclose(f);
  buf.resize(got);
  return buf;
}

int64_t count_cols(const char* line, const char* end) {
  int64_t cols = 1;
  for (const char* p = line; p < end && *p != '\n'; ++p)
    if (*p == ',') ++cols;
  return cols;
}

// A line is blank when it holds no non-whitespace character.  Blank lines
// are not rows: the NumPy fallback (np.genfromtxt) skips them, and counting
// them here would shift every subsequent row.
bool is_blank_line(const char* p, const char* end) {
  for (; p < end && *p != '\n'; ++p)
    if (*p != ' ' && *p != '\t' && *p != '\r') return false;
  return true;
}

}  // namespace

// First pass: number of data rows and columns.  skip_header skips line 1.
// Returns 0 on success, nonzero on I/O failure.
int64_t ddl_csv_dims(const char* path, int32_t skip_header, int64_t* rows,
                     int64_t* cols) {
  std::string buf = read_file(path);
  if (buf.empty()) return 1;
  const char* p = buf.data();
  const char* end = p + buf.size();
  if (skip_header) {
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
  if (p >= end) return 2;
  // column count comes from the first NON-BLANK data line (a leading blank
  // line would report cols=1 and silently mangle the whole file)
  const char* first = p;
  while (first < end && is_blank_line(first, end)) {
    while (first < end && *first != '\n') ++first;
    if (first < end) ++first;
  }
  if (first >= end) return 2;
  *cols = count_cols(first, end);
  int64_t n = 0;
  while (p < end) {
    if (!is_blank_line(p, end)) ++n;
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
  *rows = n;
  return 0;
}

// Second pass: parse into out (rows × keep_cols) where keep_cols =
// cols - drop_first_col.  Parallel across row ranges (each thread scans to
// its starting newline).  Returns number of rows parsed.
int64_t ddl_csv_parse(const char* path, int32_t skip_header,
                      int32_t drop_first_col, float* out, int64_t rows,
                      int64_t cols) {
  std::string buf = read_file(path);
  if (buf.empty()) return -1;
  const char* base = buf.data();
  const char* end = base + buf.size();
  const char* data_start = base;
  if (skip_header) {
    while (data_start < end && *data_start != '\n') ++data_start;
    if (data_start < end) ++data_start;
  }
  const int64_t keep = cols - (drop_first_col ? 1 : 0);

  // newline index so threads can jump to row boundaries (blank lines are
  // skipped — genfromtxt parity; see is_blank_line)
  std::vector<const char*> line_starts;
  line_starts.reserve(static_cast<size_t>(rows));
  for (const char* p = data_start; p < end;) {
    if (!is_blank_line(p, end)) line_starts.push_back(p);
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
  const int64_t n = std::min<int64_t>(rows, line_starts.size());

  parallel_for(n, 4096, [&](int64_t begin, int64_t endrow) {
    for (int64_t r = begin; r < endrow; ++r) {
      const char* p = line_starts[static_cast<size_t>(r)];
      const char* line_end = p;
      while (line_end < end && *line_end != '\n') ++line_end;
      for (int64_t c = 0; c < cols; ++c) {
        // newline-bounded field parse: strtof skips leading whitespace
        // INCLUDING '\n', so an empty/short field at end of line would
        // otherwise read the next row's first value (row shift)
        float v = 0.0f;
        if (p < line_end && *p != ',') {
          char* next = nullptr;
          v = std::strtof(p, &next);
          if (next == p || next > line_end) v = 0.0f;  // garbage / ran past
        }
        if (std::isnan(v)) v = 0.0f;  // fallback parity (nan_to_num)
        while (p < line_end && *p != ',') ++p;
        if (p < line_end && *p == ',') ++p;
        int64_t cc = c - (drop_first_col ? 1 : 0);
        if (cc >= 0 && cc < keep) out[r * keep + cc] = v;
      }
    }
  });
  return n;
}

// Crop (top, left, h, w) from an (H, W, C) float image and bilinearly
// resize to (out_h, out_w) — torchvision resized_crop semantics
// (align_corners=False), the PCB dataset's per-item op
// (reference CNN/dataset.py:100).
void ddl_crop_resize_bilinear(const float* img, int64_t H, int64_t W,
                              int64_t C, int64_t top, int64_t left, int64_t h,
                              int64_t w, int64_t out_h, int64_t out_w,
                              float* out) {
  const float sy = static_cast<float>(h) / static_cast<float>(out_h);
  const float sx = static_cast<float>(w) / static_cast<float>(out_w);
  parallel_for(out_h, 64, [=](int64_t begin, int64_t end_row) {
    for (int64_t oy = begin; oy < end_row; ++oy) {
      float fy = (static_cast<float>(oy) + 0.5f) * sy - 0.5f;
      fy = std::max(0.0f, std::min(fy, static_cast<float>(h - 1)));
      int64_t y0 = static_cast<int64_t>(fy);
      int64_t y1 = std::min(y0 + 1, h - 1);
      float wy = fy - static_cast<float>(y0);
      for (int64_t ox = 0; ox < out_w; ++ox) {
        float fx = (static_cast<float>(ox) + 0.5f) * sx - 0.5f;
        fx = std::max(0.0f, std::min(fx, static_cast<float>(w - 1)));
        int64_t x0 = static_cast<int64_t>(fx);
        int64_t x1 = std::min(x0 + 1, w - 1);
        float wx = fx - static_cast<float>(x0);
        for (int64_t c = 0; c < C; ++c) {
          auto at = [&](int64_t y, int64_t x) {
            return img[((top + y) * W + (left + x)) * C + c];
          };
          float v0 = at(y0, x0) * (1.0f - wx) + at(y0, x1) * wx;
          float v1 = at(y1, x0) * (1.0f - wx) + at(y1, x1) * wx;
          out[(oy * out_w + ox) * C + c] = v0 * (1.0f - wy) + v1 * wy;
        }
      }
    }
  });
}

}  // extern "C"
