"""Epoch loop with the reference's phase/metric semantics.

Reproduces the reference ``worker`` (``CNN/main.py:76-127``): per epoch a
train phase, a validation phase, LR decay (baked into the optax schedule),
and one final test phase; accuracy = argmax-match × 100 / samples; the
logged loss keeps the reference's Σ(batch-mean)/Σ(samples) formula (quirk
Q9) for log parity.

Unlike the reference (``loss.item()`` per batch forces a device sync every
step), metric scalars stay on device during the epoch and are fetched once
at phase end — dispatch stays fully async.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from distributed_deep_learning_tpu.train.state import TrainState
from distributed_deep_learning_tpu.utils.logging import PhaseLogger


@dataclasses.dataclass
class EpochResult:
    phase: str
    epoch: int | None
    accuracy: float
    loss: float
    seconds: float
    examples: int

    @property
    def examples_per_sec(self) -> float:
        return self.examples / self.seconds if self.seconds > 0 else 0.0


def _run_phase(step_fn, state, loader, *, train: bool, monitor=None):
    """Drive one phase; returns (state, totals) with one host sync at end."""
    device_metrics = []
    for x, y in loader:
        if monitor is not None:
            # cheap per-step liveness poll (an attribute read): a peer dying
            # mid-epoch surfaces HERE instead of hanging the next collective
            monitor.raise_if_failed()
        if train:
            state, m = step_fn(state, x, y)
        else:
            m = step_fn(state, x, y)
        device_metrics.append(m)
    if not device_metrics:
        return state, {"loss": 0.0, "correct": 0, "count": 0}
    summed = jax.tree.map(lambda *xs: np.sum(jax.device_get(list(xs)), axis=0),
                          *device_metrics)
    return state, summed


def _result(phase: str, epoch: int | None, totals, t0: float, t1: float) -> EpochResult:
    counter = int(totals["count"]) or 1
    return EpochResult(
        phase=phase, epoch=epoch,
        # reference formulas (CNN/main.py:94-95): acc×100/samples,
        # Σ(batch-mean loss)/samples (Q9)
        accuracy=float(totals["correct"]) * 100.0 / counter,
        loss=float(totals["loss"]) / counter,
        seconds=t1 - t0, examples=int(totals["count"]),
    )


def fit(state: TrainState, train_step, eval_step, train_loader, val_loader,
        test_loader, epochs: int, logger: PhaseLogger | None = None,
        checkpointer=None, start_epoch: int = 1, monitor=None
        ) -> tuple[TrainState, list[EpochResult]]:
    """Drive the epoch loop.  With a ``checkpointer``
    (:class:`..utils.checkpoint.Checkpointer`) the state is saved after
    every epoch (async) — pass ``start_epoch`` = last saved epoch + 1 to
    resume a preempted run.  ``monitor``
    (:class:`..utils.failures.FailureMonitor`) is polled before every step
    so a dead peer raises :class:`..utils.failures.WorkerFailure` promptly
    instead of hanging the next collective."""
    logger = logger or PhaseLogger(verbose=False)
    history: list[EpochResult] = []

    from distributed_deep_learning_tpu.utils.failures import (
        maybe_inject_failure)

    for epoch in range(start_epoch, epochs + 1):  # reference counts from 1
        maybe_inject_failure(epoch)  # chaos drill (DDL_INJECT_FAILURE)
        train_loader.set_epoch(epoch)
        t0 = logger.phase_begin("train", epoch)
        state, totals = _run_phase(train_step, state, train_loader,
                                   train=True, monitor=monitor)
        t1 = logger.clock()
        res = _result("train", epoch, totals, t0, t1)
        logger.phase_end("train", epoch, accuracy=res.accuracy, loss=res.loss)
        # beyond-reference observability: throughput counters per phase
        logger.metrics(phase="train", epoch=epoch,
                       examples_per_sec=round(res.examples_per_sec, 1),
                       examples=res.examples)
        history.append(res)

        t0 = logger.clock()
        _, totals = _run_phase(eval_step, state, val_loader, train=False,
                               monitor=monitor)
        t1 = logger.clock()
        res = _result("validation", epoch, totals, t0, t1)
        # reference prints only the validation end line (CNN/main.py:111)
        logger.phase_end("validation", epoch, accuracy=res.accuracy, loss=res.loss)
        history.append(res)

        if checkpointer is not None:
            checkpointer.save(epoch, state)

    if checkpointer is not None:
        checkpointer.wait_until_finished()

    t0 = logger.clock()
    _, totals = _run_phase(eval_step, state, test_loader, train=False)
    t1 = logger.clock()
    res = _result("test", None, totals, t0, t1)
    logger.phase_end("test", accuracy=res.accuracy, loss=res.loss)
    history.append(res)
    return state, history
