"""Typed run configuration + the reference-compatible CLI.

The reference exposes per-workload argparse flags (``getConfiguration``,
reference ``src/pytorch/CNN/main.py:47-68`` and ``LSTM/main.py:53-74``):
``-l/--nlayers -s/--size -e/--epochs -b/--batch -d/--device -w/--nworkers
-m/--mode -p/--pipeline -r/--run``.  We keep that exact surface (so a user of
the reference can switch CLIs unchanged) but parse into one frozen dataclass
instead of a loose dict / module-globals injection (reference
``MLP/main.py:52-55``).

Multi-host rank/world detection generalises the reference's MPI-env sniffing
(``CNN/main.py:62-67``): we look at JAX/TPU-standard coordinator variables as
well as OMPI/SLURM ones, and feed ``jax.distributed.initialize`` instead of
``torch.distributed.init_process_group``.
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import os
from typing import Sequence


#: --remat-policy name -> jax.checkpoint_policies attribute (None = the
#: jax.checkpoint default: recompute everything).  Lives here (jax-free)
#: so the CLI choices and train/step.py's resolver share one table.
REMAT_POLICIES = {
    "nothing": None,
    "dots": "dots_saveable",
    "dots_no_batch": "dots_with_no_batch_dims_saveable",
}

#: Canonical mesh axis order.  Lives here (jax-free, same reasoning as
#: REMAT_POLICIES) so ``--mesh`` can be validated at parse time;
#: ``runtime/mesh.py`` re-exports it as ``AXES`` and builds the actual
#: ``jax.sharding.Mesh`` in this order.
MESH_AXES = ("data", "fsdp", "stage", "model", "seq", "expert")


class Mode(str, enum.Enum):
    """Execution mode, 1:1 with the reference CLI (`-m`)."""

    SEQUENTIAL = "sequential"  # single device, plain jitted step
    MODEL = "model"            # layer-wise model parallelism over `stage` axis
    PIPELINE = "pipeline"      # GPipe-style microbatched pipeline over `stage`
    DATA = "data"              # data parallelism over `data` axis

    def __str__(self) -> str:  # argparse help rendering
        return self.value


class Device(str, enum.Enum):
    CPU = "cpu"
    GPU = "gpu"  # accepted for CLI parity with the reference; mapped to tpu
    TPU = "tpu"

    def __str__(self) -> str:
        return self.value


@dataclasses.dataclass(frozen=True)
class DistributedEnv:
    """Process topology discovered from the environment.

    Replaces the reference's `DISTRIBUTED`/rank/world env sniffing
    (``CNN/main.py:62-67``).  `coordinator` feeds
    ``jax.distributed.initialize``.
    """

    process_id: int = 0
    num_processes: int = 1
    local_process_id: int = 0
    coordinator: str | None = None

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @staticmethod
    def from_environ(env: dict[str, str] | None = None) -> "DistributedEnv":
        env = dict(os.environ) if env is None else env

        def geti(*names: str, default: int | None = None) -> int | None:
            for n in names:
                if n in env:
                    try:
                        return int(env[n])
                    except ValueError:
                        pass
            return default

        num = geti("DDL_NUM_PROCESSES", "OMPI_COMM_WORLD_SIZE", "SLURM_NTASKS",
                   "PMI_SIZE", default=1)
        pid = geti("DDL_PROCESS_ID", "OMPI_COMM_WORLD_RANK", "SLURM_PROCID",
                   "PMI_RANK", default=0)
        local = geti("DDL_LOCAL_PROCESS_ID", "OMPI_COMM_WORLD_LOCAL_RANK",
                     "SLURM_LOCALID", default=0)
        coord = env.get("DDL_COORDINATOR") or env.get("MASTER_ADDR")
        if coord is not None and ":" not in coord:
            coord = f"{coord}:{env.get('MASTER_PORT', '29500')}"
        return DistributedEnv(
            process_id=pid or 0,
            num_processes=num or 1,
            local_process_id=local or 0,
            coordinator=coord,
        )


@dataclasses.dataclass(frozen=True)
class Config:
    """One run's full configuration.

    Field-to-flag mapping follows the reference exactly (``CNN/main.py:49-57``):

    ==============  ====  =========================================
    field           flag  reference meaning
    ==============  ====  =========================================
    num_layers      -l    hidden/dense/LSTM layer count
    size            -s    hidden width / bn_size
    epochs          -e    training epochs
    batch_size      -b    global batch size
    device          -d    cpu | gpu (we add tpu; gpu aliases tpu)
    num_workers     -w    host-side data-loader worker threads
    mode            -m    sequential | model | pipeline | data
    microbatch      -p    pipeline microbatch SIZE (not count) —
                          preserves the reference's `-p` semantics
                          (``CNN/model.py:212`` splits by size)
    world_size      -r    local device/process fan-out for `data`
    ==============  ====  =========================================
    """

    num_layers: int = 1
    size: int = 38
    epochs: int = 10    # reference default (CNN/main.py:51)
    batch_size: int = 32  # reference default (CNN/main.py:52)
    device: Device = Device.TPU
    num_workers: int = 0
    mode: Mode = Mode.SEQUENTIAL
    microbatch: int | None = 2  # reference -p default; used only in pipeline mode
    world_size: int = 1

    # --- beyond-reference knobs (all default to reference behaviour) ---
    seed: int = 42                      # reference pins torch.manual_seed(42)
    learning_rate: float = 1e-3
    dtype: str = "float32"              # "bfloat16" for the TPU fast path
    num_stages: int | None = None       # MP/PP stage count (default: #devices)
    mesh_shape: dict[str, int] | None = None  # explicit mesh, e.g. {"data":4,"stage":2}
    double_softmax: bool = False        # reference quirk Q4 (Softmax + CE); off → logits+CE
    sync_in_local_data_mode: bool = True  # reference quirk Q1 fixed by default
    zero: str = "none"                  # optimizer/param sharding: none|1|fsdp
    grad_compress: str = "none"         # gradient all-reduce wire format:
                                        #   none|bf16|int8 (train/compress.py)
    comm: str = "none"                  # FSDP collective wire format:
                                        #   none|bf16|int8 (parallel/collectives.py)
    comm_overlap: bool = False          # ring-overlapped FSDP collectives
    grad_accum: int = 1                 # gradient-accumulation microsteps
    dropout: float = 0.0                # train-time dropout rate (north-star models)
    remat: bool = False                 # rematerialise activations in backward
    remat_policy: str = "nothing"       # what backward may keep (train/step.py)
    checkpoint_dir: str | None = None
    checkpoint_every: int = 0           # also save every N train steps (0 = epoch-only)
    resume: bool = False
    profile_dir: str | None = None
    data_dir: str | None = None         # real-data root (ImageFolder layout)
    packed_cache: str | None = None     # packed sample-cache artifact
                                        #   (data/packed.py; overrides the
                                        #   workload's dataset builder)
    image_size: int = 224               # decode size for --data-dir images
    stem_s2d: bool = False              # space-to-depth ResNet stem (TPU opt)
    attention: str = "auto"             # auto|dense|flash (transformer family)
    attention_window: int | None = None  # sliding-window size (flash, causal)
    optimizer: str = "auto"             # auto|sgd|momentum|adam|adamw|...
    generate_tokens: int = 0            # gpt: sample N tokens post-train
    serve: bool = False                 # gpt: post-train continuous-batching
                                        #   serving demo (serve/engine.py)
    max_slots: int = 8                  # serving: concurrent decode slots
    prefill_buckets: tuple[int, ...] | None = None  # serving: prefill pad
                                        #   lengths (None = powers of two)
    paged: bool = False                 # serving: paged-KV engine with
                                        #   prefix reuse + chunked prefill
                                        #   (serve/paged.py, PagedEngine)
    kv_block_size: int = 16             # serving: paged-KV block tokens
    prefill_chunk: int = 32             # serving: chunked-prefill width
    draft: int = 0                      # serving: truncated-draft layers
                                        #   for speculative decoding (0=off)
    spec_k: int = 4                     # serving: draft tokens per round
    slo_ttft_ms: float | None = None    # serving: per-request TTFT SLO
    slo_e2e_ms: float | None = None     # serving: per-request e2e SLO
    serve_deadline_ms: float | None = None  # supervised serving: hard
                                        #   per-request wall deadline
                                        #   (serve/supervisor.py)
    serve_retries: int = 2              # supervised serving: engine-fault
                                        #   survivals allowed per request
    reload_watch: str | None = None     # supervised serving: hot weight-
                                        #   reload watch directory
                                        #   (serve/reload.py)
    canary_slots: int = 2               # supervised serving: slots routed
                                        #   to candidate weights before
                                        #   promote/rollback
    admission: dict | None = None       # supervised serving: admission-
                                        #   control knobs (--admission
                                        #   "depth=16,itl-p99-ms=200")
    kv_dtype: str | None = None         # serving: KV-cache storage dtype
                                        #   bf16|int8 (int8 = per-position
                                        #   scales in the block pools,
                                        #   paged engine only; serve/quant)
    weight_dtype: str | None = None     # serving: decode weight storage
                                        #   dtype bf16|int8 (per-channel
                                        #   scales, dequant fused into the
                                        #   compiled decode matmuls)
    replicas: int = 1                   # fleet serving: paged-engine
                                        #   replicas behind the prefix-
                                        #   affinity router (serve/fleet.py)
    priority_classes: tuple | None = None  # fleet serving: priority mix
                                        #   ((prio, frac), ...) parsed from
                                        #   --priority-classes "0=0.25,..."
    spill_dir: str | None = None        # fleet serving: host directory for
                                        #   preempted-slot KV spill files
                                        #   (engine preemption audit trail)
    autoscale: dict | None = None       # fleet serving: elastic replica-
                                        #   count knobs (--autoscale
                                        #   "min=1,max=4,patience=2")
    evacuate_on: str = "off"            # fleet serving: live mid-request
                                        #   slot evacuation trigger —
                                        #   off | degraded | hotspot
                                        #   (serve/rebalance.py)
    disagg: bool = False                # serving: disaggregate the replica
                                        #   into prefill + decode device
                                        #   pools joined by KV-block
                                        #   migration (serve/disagg.py)
    pool_elastic: bool = False          # disagg serving: move a worker
                                        #   between prefill/decode pools
                                        #   on sustained prefill_util skew
    prefill_workers: int = 1            # serving: devices in the disagg
                                        #   prefill pool (the rest decode)
    migrate: str = "host"               # serving: where preempted KV
                                        #   parks — host (npz-auditable
                                        #   arrays) or device (device-to-
                                        #   device, digest-audited)
    publish_weights: str | None = None  # checkpointing: atomically publish
                                        #   verified saves for serving hot
                                        #   reload (serve/reload.py)
    pos_embedding: str = "learned"      # learned | rope (gpt)
    num_kv_heads: int | None = None     # grouped-query attention (gpt)
    label_smoothing: float = 0.0        # token-CE smoothing (LM families)
    pipeline_schedule: str = "gpipe"    # gpipe | 1f1b | interleaved
    virtual_stages: int = 2             # chunks/device (interleaved)
    lr_schedule: str = "none"           # none|cosine|rsqrt|step (north stars)
    warmup_steps: int | None = None     # cosine/rsqrt warmup; None = 5% auto
    clip_norm: float | None = None      # global-norm gradient clipping
    metrics_file: str | None = None     # JSONL event sink (rank 0)
    obs: bool = False                   # unified run telemetry (obs/):
                                        #   goodput/MFU accounting + JSONL
                                        #   event stream
    obs_file: str | None = None         # telemetry sidecar path (default
                                        #   obs_events.jsonl; non-rank-0
                                        #   processes get .rankN suffix)
    obs_trace: str | None = None        # span-trace export path (Chrome/
                                        #   Perfetto JSON; implies the
                                        #   per-step/request Tracer)
    obs_rotate_mb: float | None = None  # size-cap the JSONL sidecar:
                                        #   rotate at N MB, fsync on
                                        #   rollover (obs/export.py)
    obs_blackbox: str | None = None     # arm a crash flight recorder:
                                        #   bounded event ring dumped
                                        #   here on sentinel trip /
                                        #   fatal signal / exit
    sentinel: str = "off"               # anomaly sentinel policy:
                                        #   off|skip|rollback|halt
                                        #   (train/sentinel.py)
    sentinel_window: int = 32           # EMA horizon for spike detection
    sentinel_factor: float = 10.0       # spike threshold (x running mean)
    elastic: bool = False               # checkpointed restart on failure
    reshard: bool = False               # cross-topology resume: restore a
                                        #   checkpoint saved on a different
                                        #   mesh, re-planning via tune/
                                        #   (reshard/)
    target_mesh: dict[str, int] | None = None  # --target-mesh: pin the
                                        #   restart mesh instead of
                                        #   re-planning
    heartbeat_dir: str | None = None    # shared dir for liveness heartbeats
    heartbeat_timeout: float = 30.0     # seconds before a peer counts as dead
    autotune: bool = False              # search the plan lattice (tune/)
                                        #   before training and train under
                                        #   the best measured plan
    plan_file: str | None = None        # plan artifact path: --plan loads and
                                        #   applies it; with --autotune the
                                        #   search result is written here
    distributed: DistributedEnv = dataclasses.field(default_factory=DistributedEnv)

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

    @property
    def pipeline_enabled(self) -> bool:
        return self.mode is Mode.PIPELINE


# Per-workload -l/-s defaults, matching each reference main
# (CNN/main.py:49-50 → 2 dense blocks, bn_size 4; LSTM/main.py:55-56 →
# 1 hidden LSTM layer, width 128; MLP/main.py:42 → 1 hidden layer, the MLP
# has no -s flag and a fixed width of 38).
WORKLOAD_DEFAULTS: dict[str, dict[str, int]] = {
    "cnn": {"nlayers": 2, "size": 4},
    "lstm": {"nlayers": 1, "size": 128},
    "mlp": {"nlayers": 1, "size": 38},
    "mnist": {"nlayers": 2, "size": 32},
    # north-star families (BASELINE.json): -s is depth (resnet) / width
    "resnet": {"nlayers": 4, "size": 18},
    "transformer": {"nlayers": 6, "size": 512},
    "bert": {"nlayers": 12, "size": 768},
    "moe": {"nlayers": 4, "size": 256},
    "gpt": {"nlayers": 12, "size": 768},
}


def build_parser(workload: str = "") -> argparse.ArgumentParser:
    """The reference CLI (``getConfiguration``), plus framework extensions.

    Shared defaults match the reference exactly (``CNN/main.py:49-57``):
    ``-e 10 -b 32 -p 2 -r 1 -m sequential``.  ``-d`` defaults to ``tpu``
    (documented divergence: this *is* the TPU backend; ``gpu`` is accepted
    and aliased to tpu).
    """
    wd = WORKLOAD_DEFAULTS.get(workload.lower(), WORKLOAD_DEFAULTS["mlp"])
    p = argparse.ArgumentParser(
        prog=workload or "ddl-tpu",
        description="TPU-native distributed deep learning trainer",
    )
    p.add_argument("-l", "--nlayers", type=int, default=wd["nlayers"],
                   help="number of hidden/dense/LSTM layers")
    p.add_argument("-s", "--size", type=int, default=wd["size"],
                   help="hidden size / bottleneck size")
    p.add_argument("-e", "--epochs", type=int, default=10)
    p.add_argument("-b", "--batch", type=int, default=32,
                   help="global batch size")
    p.add_argument("-d", "--device", choices=[d.value for d in Device],
                   default="tpu")
    p.add_argument("-w", "--nworkers", type=int, default=0,
                   help="host-side data loading workers")
    p.add_argument("-m", "--mode", choices=[m.value for m in Mode],
                   default="sequential")
    p.add_argument("-p", "--pipeline", type=int, default=2,
                   help="pipeline microbatch size (reference -p semantics; "
                        "ignored unless -m pipeline)")
    p.add_argument("-r", "--run", type=int, default=1,
                   help="world size for local data-parallel fan-out")
    # framework extensions
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--dtype", choices=["float32", "bfloat16"], default="float32")
    p.add_argument("--nstages", type=int, default=None,
                   help="number of model/pipeline stages (default: all devices)")
    p.add_argument("--mesh", type=str, default=None,
                   help="explicit mesh, e.g. 'data=4,stage=2'")
    p.add_argument("--double-softmax", action="store_true",
                   help="replicate reference quirk Q4 (Softmax into CE loss)")
    p.add_argument("--no-sync", dest="sync", action="store_false",
                   help="replicate reference quirk Q1 (local data mode trains "
                        "independent replicas)")
    p.add_argument("--remat", action="store_true",
                   help="recompute activations in backward (jax.checkpoint) "
                        "— trades FLOPs for HBM")
    p.add_argument("--remat-policy", dest="remat_policy", default="nothing",
                   choices=sorted(REMAT_POLICIES),
                   help="with --remat: what backward may reuse — 'nothing' "
                        "recomputes all; 'dots'/'dots_no_batch' keep matmul "
                        "outputs so only elementwise chains recompute")
    p.add_argument("--dropout", type=float, default=0.0,
                   help="dropout rate for transformer/bert workloads "
                        "(seeded per-step PRNG streams; 0 = deterministic)")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="split each batch into this many sequential "
                        "microbatches, accumulating gradients")
    p.add_argument("--zero", choices=["none", "1", "fsdp"], default="none",
                   help="shard optimizer state (ZeRO-1) or params+optimizer "
                        "(fsdp) over the fsdp/data mesh axes")
    p.add_argument("--grad-compress", choices=["none", "bf16", "int8"],
                   default="none",
                   help="compress the data-parallel gradient all-reduce: "
                        "bf16 halves wire bytes; int8 is common-scale "
                        "quantization with int32 reduction (EQuARX-style "
                        "numerics)")
    p.add_argument("--comm", choices=["none", "bf16", "int8"],
                   default="none",
                   help="with --zero fsdp: quantize the explicit param "
                        "all-gather / grad reduce-scatter collectives "
                        "(bf16 halves wire bytes; int8 quarters them with "
                        "per-leaf error-feedback residuals; "
                        "parallel/collectives.py)")
    p.add_argument("--comm-overlap", action="store_true",
                   help="with --comm: run the FSDP collectives as "
                        "double-buffered ppermute rings so each chunk's "
                        "transfer overlaps the previous chunk's compute")
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="also checkpoint every N train steps (0 = per "
                        "epoch only); a preemption then costs at most N "
                        "steps — resume replays the loader to the exact "
                        "batch")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--profile-dir", type=str, default=None)
    p.add_argument("--data-dir", type=str, default=None,
                   help="train on a real ImageFolder-layout dataset "
                        "(root/<class>/*.jpg) instead of the synthetic twin; "
                        "-w sets the decode thread count")
    p.add_argument("--packed-cache", type=str, default=None, metavar="FILE",
                   help="train from a packed pre-decoded sample cache "
                        "(scripts/pack_dataset.py artifact): the cache is "
                        "memory-mapped and batches assemble with zero "
                        "per-sample Python work, instead of re-decoding "
                        "--data-dir files every epoch")
    p.add_argument("--image-size", type=int, default=224,
                   help="square decode size for --data-dir images")
    p.add_argument("--window", dest="attention_window", type=int,
                   default=None, metavar="W",
                   help="sliding-window attention: each position attends "
                        "its last W tokens only (flash kernel, causal "
                        "models; O(T*W) instead of O(T^2))")
    p.add_argument("--stem-s2d", action="store_true",
                   help="space-to-depth ResNet stem: pack 2x2 input patches "
                        "into channels and run the mathematically equivalent "
                        "4x4-s1 stem conv (MXU-friendly; ImageNet-size "
                        "stems only)")
    p.add_argument("--attention", choices=["auto", "dense", "flash"],
                   default="auto",
                   help="attention implementation for transformer-family "
                        "models: auto = Pallas flash kernel on TPU, dense "
                        "elsewhere")
    p.add_argument("--optimizer",
                   choices=["auto", "sgd", "momentum", "adam", "adamw",
                            "adafactor", "lamb"],
                   default="auto",
                   help="override the workload's default optimizer: "
                        "adafactor = sublinear-memory factored second "
                        "moments (the TPU big-model staple), lamb = "
                        "layerwise-adaptive large-batch; auto keeps the "
                        "per-workload recipe (sgd+momentum for vision, "
                        "adamw for LMs)")
    p.add_argument("--label-smoothing", type=float, default=0.0,
                   metavar="EPS",
                   help="label smoothing for the token cross-entropy "
                        "(transformer/bert/moe/gpt; 0.1 = the "
                        "transformer-base recipe)")
    p.add_argument("--kv-heads", dest="num_kv_heads", type=int,
                   default=None, metavar="K",
                   help="gpt grouped-query attention: K key/value heads "
                        "shared by the query heads (must divide them; "
                        "shrinks the KV cache by heads/K)")
    p.add_argument("--pos", dest="pos_embedding",
                   choices=["learned", "rope"], default="learned",
                   help="gpt position encoding: learned absolute table or "
                        "parameter-free rotary (RoPE, relative positions)")
    p.add_argument("--generate", dest="generate_tokens", type=int,
                   default=0, metavar="N",
                   help="gpt: after training, print N-token greedy "
                        "continuations of two dataset prompts (KV-cached "
                        "decode; a smoke sample — the prompts are usually "
                        "training rows, not held-out data)")
    p.add_argument("--serve", action="store_true",
                   help="gpt: after training, serve a mixed-length batch "
                        "of dataset prompts through the continuous-"
                        "batching engine (slot-based KV cache, compile-"
                        "once decode) and log tokens/sec + occupancy — "
                        "the serving sibling of --generate")
    p.add_argument("--max-slots", dest="max_slots", type=int, default=8,
                   metavar="S",
                   help="serving: concurrent decode slots (the engine's "
                        "static batch dimension; throughput tracks slot "
                        "occupancy)")
    p.add_argument("--prefill-buckets", dest="prefill_buckets", type=str,
                   default=None, metavar="L1,L2,...",
                   help="serving: comma-separated prompt-padding bucket "
                        "lengths — one compiled prefill program each "
                        "(default: powers of two up to the cache length)")
    p.add_argument("--paged", action="store_true",
                   help="serving: use the paged-KV engine — block pools "
                        "with rolling-hash prefix reuse (shared prompt "
                        "prefixes prefill once), chunked prefill "
                        "interleaved with decode, optional speculative "
                        "decoding via --draft")
    p.add_argument("--kv-block-size", dest="kv_block_size", type=int,
                   default=16, metavar="B",
                   help="paged serving: tokens per KV block (prefix "
                        "sharing granularity; smaller = more sharing, "
                        "more gather work)")
    p.add_argument("--prefill-chunk", dest="prefill_chunk", type=int,
                   default=32, metavar="C",
                   help="paged serving: prefill slice width — in-flight "
                        "decode streams stall at most ~one chunk of "
                        "compute per token, whatever the prompt length")
    p.add_argument("--draft", type=int, default=0, metavar="N",
                   help="paged serving: speculative decoding with a "
                        "draft built from the target's first N layers "
                        "(shared weights; greedy outputs stay "
                        "bit-identical); 0 disables")
    p.add_argument("--spec-k", dest="spec_k", type=int, default=4,
                   metavar="K",
                   help="paged serving: draft tokens proposed per round "
                        "(verified in one batched target forward)")
    p.add_argument("--slo-ttft-ms", dest="slo_ttft_ms", type=float,
                   default=None, metavar="MS",
                   help="serving: per-request time-to-first-token SLO; "
                        "attainment is reported in the serve stats")
    p.add_argument("--slo-e2e-ms", dest="slo_e2e_ms", type=float,
                   default=None, metavar="MS",
                   help="serving: per-request end-to-end latency SLO")
    p.add_argument("--serve-deadline-ms", dest="serve_deadline_ms",
                   type=float, default=None, metavar="MS",
                   help="supervised serving: hard per-request wall "
                        "deadline — a request a fault loop holds past "
                        "this errors out instead of replaying forever "
                        "(serve/supervisor.py; implies supervision)")
    p.add_argument("--serve-retries", dest="serve_retries", type=int,
                   default=2, metavar="N",
                   help="supervised serving: engine faults a request may "
                        "survive (with zero-loss replay) before it is "
                        "errored out")
    p.add_argument("--reload-watch", dest="reload_watch", type=str,
                   default=None, metavar="DIR",
                   help="supervised serving: watch DIR for atomically "
                        "published weights (serve/reload.py) and hot-swap "
                        "them between ticks — canary first, integrity-"
                        "manifest verified, corrupt saves quarantined")
    p.add_argument("--canary-slots", dest="canary_slots", type=int,
                   default=2, metavar="N",
                   help="supervised serving: decode slots routed to "
                        "candidate weights while old/new agreement and "
                        "logprob drift decide promote vs rollback "
                        "(0 = swap verified weights directly)")
    p.add_argument("--admission", type=str, default=None,
                   metavar="K=V,...",
                   help="supervised serving: SLO-aware admission control "
                        "— 'depth=16,itl-p99-ms=200,shed-priority=2' "
                        "(keys: depth, itl-p99-ms, shed-priority, "
                        "patience, cool); degrades quality (spec decode "
                        "off, chunk budget down) before shedding, and "
                        "never sheds priority-0 requests")
    p.add_argument("--kv-dtype", dest="kv_dtype", type=str, default=None,
                   metavar="DT",
                   help="serving: KV-cache storage dtype, bf16 or int8 "
                        "(int8 keeps per-position scales in the block "
                        "pools — requires --paged; the spec-decode draft "
                        "pool inherits it; unset = full precision)")
    p.add_argument("--weight-dtype", dest="weight_dtype", type=str,
                   default=None, metavar="DT",
                   help="serving: decode weight storage dtype, bf16 or "
                        "int8 (per-output-channel scales; dequantization "
                        "fuses into the compiled decode matmuls, so no "
                        "full-precision copy exists at rest)")
    p.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="fleet serving: run N paged-engine replicas "
                        "behind the health-checked prefix-affinity "
                        "router (serve/fleet.py) — crash-quarantine with "
                        "zero-loss cross-replica replay; requires "
                        "--paged when N > 1")
    p.add_argument("--priority-classes", dest="priority_classes",
                   type=str, default=None, metavar="P=F,...",
                   help="fleet serving: request priority mix, e.g. "
                        "'0=0.25,1=0.5,2=0.25' (priority=fraction, "
                        "fractions sum to 1); under slot/block pressure "
                        "higher-priority arrivals preempt the lowest-"
                        "priority slots (KV spilled to host, resumed "
                        "bit-identically); priority 0 is never "
                        "preempted or shed; requires --paged")
    p.add_argument("--spill-dir", dest="spill_dir", type=str,
                   default=None, metavar="DIR",
                   help="fleet serving: also write each preempted "
                        "slot's spilled KV to DIR as an npz audit "
                        "trail (resume itself stays in host memory); "
                        "requires --priority-classes")
    p.add_argument("--autoscale", type=str, default=None,
                   metavar="K=V,...",
                   help="fleet serving: elastic replica autoscaling, "
                        "e.g. 'min=1,max=4,patience=2,cool=2' — "
                        "patience consecutive hot rounds warm one new "
                        "replica from the published weights (prefix-"
                        "warmed via clone_prefix), cool consecutive "
                        "cold rounds retire one through the drain "
                        "protocol (stop placement, evacuate open "
                        "slots, retire); requires --replicas > 1")
    p.add_argument("--evacuate-on", dest="evacuate_on",
                   choices=["off", "degraded", "hotspot"],
                   default="off",
                   help="fleet serving: live mid-request slot "
                        "evacuation — on 'degraded' a health-degraded "
                        "replica's open slots migrate (digest-verified "
                        "committed KV) to healthy peers and resume "
                        "bit-identically; 'hotspot' also evacuates on "
                        "sustained per-replica latency skew; requires "
                        "--replicas > 1")
    p.add_argument("--disagg", action="store_true",
                   help="serving: disaggregate the replica into a "
                        "prefill worker pool (chunked, compile-once per "
                        "chunk width) and decode workers on separate "
                        "devices, joined by device-to-device KV-block "
                        "migration (serve/disagg.py); requires --paged "
                        "and at least 2 local devices")
    p.add_argument("--prefill-workers", dest="prefill_workers", type=int,
                   default=1, metavar="N",
                   help="disaggregated serving: devices in the prefill "
                        "pool; the remaining visible devices become "
                        "decode workers, so N must leave at least one "
                        "(requires --disagg)")
    p.add_argument("--pool-elastic", dest="pool_elastic",
                   action="store_true",
                   help="disaggregated serving: after the run, judge "
                        "the measured prefill_util against the pool "
                        "rebalancer's hysteresis and reassign one idle "
                        "worker between the prefill and decode pools "
                        "when the skew is sustained (serve/autoscaler."
                        "PoolRebalancer); requires --disagg")
    p.add_argument("--migrate", choices=["host", "device"],
                   default="host",
                   help="serving preemption: where a preempted slot's "
                        "KV parks — host (npz-auditable arrays, the "
                        "default) or device (chunked device-to-device "
                        "block migration with end-to-end digest audit; "
                        "needs a second local device)")
    p.add_argument("--publish-weights", dest="publish_weights", type=str,
                   default=None, metavar="DIR",
                   help="checkpointing: after each verified save, "
                        "atomically publish the params to DIR in the "
                        "serve/reload.py manifest format, so serving "
                        "fleets watching it (--reload-watch) hot-swap "
                        "the new weights; requires --checkpoint-dir")
    p.add_argument("--schedule", dest="lr_schedule",
                   choices=["none", "cosine", "rsqrt", "step"],
                   default="none",
                   help="learning-rate schedule: cosine (ResNet/BERT "
                        "recipe), rsqrt (transformer-base Noam), step "
                        "(the reference's StepLR)")
    p.add_argument("--warmup", dest="warmup_steps", type=int, default=None,
                   help="warmup steps for --schedule cosine/rsqrt "
                        "(default: 5%% of total steps; 0 disables warmup)")
    p.add_argument("--clip-norm", type=float, default=None,
                   help="clip gradients to this global norm before the "
                        "optimizer update (per-stage norm in staged MPMD "
                        "modes)")
    p.add_argument("--metrics-file", type=str, default=None,
                   help="append one JSON object per phase/metric event "
                        "(structured sibling of the reference log stream)")
    p.add_argument("--obs", action="store_true",
                   help="unified run telemetry (obs/): per-step span "
                        "recording rolled up into a goodput breakdown "
                        "(productive/input-stall/checkpoint/recovery/"
                        "compile), MFU from the compiled step's cost "
                        "model, and a JSONL event stream readable by "
                        "scripts/obs_report.py")
    p.add_argument("--obs-file", type=str, default=None, metavar="PATH",
                   help="telemetry event-stream path (default "
                        "obs_events.jsonl; requires --obs)")
    p.add_argument("--obs-trace", type=str, default=None, metavar="PATH",
                   help="also record per-step causal spans and export "
                        "them here as Chrome/Perfetto trace JSON "
                        "(load in ui.perfetto.dev; requires --obs)")
    p.add_argument("--obs-rotate-mb", type=float, default=None,
                   metavar="MB",
                   help="size-cap the telemetry stream: rotate the "
                        "JSONL sidecar at this many MB, fsyncing each "
                        "closed segment (requires --obs)")
    p.add_argument("--obs-blackbox", type=str, default=None,
                   metavar="PATH",
                   help="arm a crash flight recorder: keep a bounded "
                        "in-memory ring of recent events and dump it "
                        "here on sentinel anomaly, SLO breach, fatal "
                        "signal or process exit (requires --obs)")
    p.add_argument("--pipeline-schedule",
                   choices=["gpipe", "1f1b", "interleaved"],
                   default="gpipe",
                   help="SPMD pipeline schedule (-m pipeline, transformer/"
                        "bert/gpt): gpipe = fill-drain with scan-transpose "
                        "backward; 1f1b = one-forward-one-backward with "
                        "O(stages) activation residency; interleaved = "
                        "1f1b with --virtual-stages model chunks per "
                        "device (Megatron-style, ~V x smaller bubble)")
    p.add_argument("--virtual-stages", type=int, default=2,
                   help="model chunks per device for --pipeline-schedule "
                        "interleaved (layers must divide nstages x this)")
    p.add_argument("--sentinel", choices=["off", "skip", "rollback", "halt"],
                   default="off",
                   help="on-device anomaly sentinel: detect non-finite "
                        "loss/grads and grad-norm/loss spikes inside the "
                        "jitted step and contain the update before it can "
                        "poison params — 'skip' drops the bad batch and "
                        "continues, 'rollback' restores the last checkpoint "
                        "with the bad step skipped (needs --elastic), "
                        "'halt' stops the run with clean state")
    p.add_argument("--sentinel-window", type=int, default=32, metavar="N",
                   help="sentinel EMA horizon in steps for the running "
                        "grad-norm/loss means spike detection compares "
                        "against")
    p.add_argument("--sentinel-factor", type=float, default=10.0,
                   metavar="X",
                   help="sentinel spike threshold: a step whose grad norm "
                        "or loss exceeds X times its running mean is "
                        "anomalous")
    p.add_argument("--elastic", action="store_true",
                   help="restart from the last checkpoint on worker failure "
                        "or runtime error (requires --checkpoint-dir)")
    p.add_argument("--reshard", action="store_true",
                   help="cross-topology resume: restore the checkpoint even "
                        "if it was saved on a different mesh, re-planning "
                        "for the surviving devices via tune/ (requires "
                        "--resume or --elastic)")
    p.add_argument("--target-mesh", type=str, default=None, metavar="SHAPE",
                   help="with --reshard: restore onto exactly this mesh "
                        "(same axis=N syntax as --mesh) instead of "
                        "re-planning")
    p.add_argument("--heartbeat-dir", type=str, default=None,
                   help="shared directory for liveness heartbeats; with "
                        "--elastic, dead peers abort the step promptly "
                        "instead of hanging the collective")
    p.add_argument("--heartbeat-timeout", type=float, default=30.0)
    p.add_argument("--autotune", action="store_true",
                   help="search the mesh x microbatch x remat x ZeRO plan "
                        "lattice (tune/) with memory-model pruning and "
                        "measured trials, write the winning plan artifact "
                        "(--plan sets the path), then train under it")
    p.add_argument("--plan", dest="plan_file", type=str, default=None,
                   metavar="FILE",
                   help="apply a plan artifact from a previous --autotune "
                        "run (rejected if its key does not match this "
                        "workload/geometry/topology); with --autotune, "
                        "where to write the search result")
    return p


def parse_buckets_arg(text: str | None) -> tuple[int, ...] | None:
    """``--prefill-buckets`` string → ascending lengths, validated at
    parse time (mirrors :func:`parse_mesh_arg`: a bad flag is an
    argparse-style error at the CLI boundary, not a traceback from the
    engine mid-run)."""
    if not text:
        return None
    try:
        buckets = tuple(int(b) for b in text.split(","))
    except ValueError:
        raise SystemExit(f"--prefill-buckets {text!r}: expected "
                         "comma-separated integers") from None
    if any(b < 1 for b in buckets):
        raise SystemExit(f"--prefill-buckets {text!r}: lengths must be "
                         ">= 1")
    for a, b in zip(buckets, buckets[1:]):
        if b == a:
            raise SystemExit(f"--prefill-buckets {text!r}: duplicate "
                             f"bucket {a} (each bucket is one compiled "
                             "prefill program; listing it twice is "
                             "always a mistake)")
        if b < a:
            raise SystemExit(f"--prefill-buckets {text!r}: lengths must "
                             f"be strictly ascending, got {b} after {a}")
    return buckets


#: ``--admission`` spec keys → (AdmissionController kwarg, converter,
#: minimum).  Kept here so a typo'd knob dies at the CLI boundary with
#: the full key list, not as a TypeError from the controller mid-serve.
_ADMISSION_KEYS = {
    "depth": ("max_queue_depth", int, 1),
    "itl-p99-ms": ("itl_p99_ms", float, 1e-9),
    "shed-priority": ("shed_priority", int, 1),
    "patience": ("patience", int, 1),
    "cool": ("cool", int, 1),
}


def parse_admission_arg(text: str | None,
                        flag: str = "--admission") -> dict | None:
    """``--admission`` string → :class:`..serve.admission.
    AdmissionController` kwargs, validated at parse time (mirrors
    :func:`parse_mesh_arg`).  Example:
    ``"depth=16,itl-p99-ms=200,shed-priority=2"``."""
    if not text:
        return None
    out: dict = {}
    for part in text.split(","):
        key, _, val = part.strip().partition("=")
        if key not in _ADMISSION_KEYS:
            raise SystemExit(
                f"{flag}: unknown key {key!r} in entry {part!r}; known "
                f"keys: {', '.join(sorted(_ADMISSION_KEYS))}")
        name, conv, lo = _ADMISSION_KEYS[key]
        if name in out:
            raise SystemExit(f"{flag}: key {key!r} given twice")
        try:
            v = conv(val)
        except ValueError:
            raise SystemExit(f"{flag}: {key}={val!r} is not a valid "
                             f"{conv.__name__}") from None
        if v < lo:
            raise SystemExit(f"{flag}: {key}={val!r} must be >= {lo}")
        out[name] = v
    return out


#: ``--autoscale`` spec keys → (FleetAutoscaler kwarg, converter,
#: minimum).  Same contract as ``_ADMISSION_KEYS``: a typo'd knob dies
#: at the CLI boundary with the full key list, not as a TypeError from
#: the autoscaler mid-serve.
_AUTOSCALE_KEYS = {
    "min": ("min_replicas", int, 1),
    "max": ("max_replicas", int, 1),
    "patience": ("patience", int, 1),
    "cool": ("cool", int, 1),
}


def parse_autoscale_arg(text: str | None,
                        flag: str = "--autoscale") -> dict | None:
    """``--autoscale`` string → :class:`..serve.autoscaler.
    FleetAutoscaler` kwargs, validated at parse time (mirrors
    :func:`parse_admission_arg`).  Example:
    ``"min=1,max=4,patience=2,cool=2"``."""
    if not text:
        return None
    out: dict = {}
    for part in text.split(","):
        key, _, val = part.strip().partition("=")
        if key not in _AUTOSCALE_KEYS:
            raise SystemExit(
                f"{flag}: unknown key {key!r} in entry {part!r}; known "
                f"keys: {', '.join(sorted(_AUTOSCALE_KEYS))}")
        name, conv, lo = _AUTOSCALE_KEYS[key]
        if name in out:
            raise SystemExit(f"{flag}: key {key!r} given twice")
        try:
            v = conv(val)
        except ValueError:
            raise SystemExit(f"{flag}: {key}={val!r} is not a valid "
                             f"{conv.__name__}") from None
        if v < lo:
            raise SystemExit(f"{flag}: {key}={val!r} must be >= {lo}")
        out[name] = v
    if ("min_replicas" in out and "max_replicas" in out
            and out["max_replicas"] < out["min_replicas"]):
        raise SystemExit(f"{flag}: max={out['max_replicas']} < "
                         f"min={out['min_replicas']} (the fleet cannot "
                         "be smaller than its floor)")
    return out


def parse_priority_classes(text: str | None,
                           flag: str = "--priority-classes"
                           ) -> tuple | None:
    """``--priority-classes`` string → ``LoadSpec.priority_classes``
    tuple, validated at parse time (mirrors :func:`parse_admission_arg`).
    Example: ``"0=0.25,1=0.5,2=0.25"`` → ``((0, 0.25), (1, 0.5),
    (2, 0.25))``."""
    if not text:
        return None
    out: list[tuple[int, float]] = []
    seen: set[int] = set()
    for part in text.split(","):
        key, _, val = part.strip().partition("=")
        if not val:
            raise SystemExit(f"{flag}: bad entry {part!r}; expected "
                             "'<priority>=<fraction>', e.g. '0=0.25'")
        try:
            prio = int(key)
        except ValueError:
            raise SystemExit(f"{flag}: priority {key!r} is not an "
                             "integer") from None
        if prio < 0:
            raise SystemExit(f"{flag}: priority {prio} must be >= 0 "
                             "(0 is the most-protected class)")
        if prio in seen:
            raise SystemExit(f"{flag}: priority {prio} given twice")
        seen.add(prio)
        try:
            frac = float(val)
        except ValueError:
            raise SystemExit(f"{flag}: fraction {val!r} for priority "
                             f"{prio} is not a number") from None
        if frac < 0:
            raise SystemExit(f"{flag}: fraction {frac} for priority "
                             f"{prio} must be >= 0")
        out.append((prio, frac))
    total = sum(f for _, f in out)
    if abs(total - 1.0) > 1e-6:
        raise SystemExit(f"{flag}: fractions must sum to 1, got "
                         f"{total:g}")
    return tuple(out)


def parse_mesh_arg(text: str | None,
                   flag: str = "--mesh") -> dict[str, int] | None:
    """``--mesh`` string → shape dict, validated at parse time.

    A bad mesh string is an argparse-style error naming the known axes —
    not a ``ValueError`` traceback from ``MeshSpec`` deep inside startup.
    The device-count constraint (axis product vs. available devices) is
    checked later by ``MeshSpec.resolve``, which knows the topology.
    ``flag`` names the offending option in the error (``--target-mesh``
    reuses this exact validation).
    """
    if not text:
        return None
    shape: dict[str, int] = {}
    for part in text.split(","):
        axis, _, n = part.partition("=")
        axis = axis.strip()
        if not n:
            raise SystemExit(f"{flag}: bad entry {part!r}; expected axis=N "
                             f"with axis one of {', '.join(MESH_AXES)}")
        if axis not in MESH_AXES:
            raise SystemExit(f"{flag}: unknown axis {axis!r}; known axes: "
                             f"{', '.join(MESH_AXES)}")
        if axis in shape:
            raise SystemExit(f"{flag}: axis {axis!r} given twice")
        try:
            size = int(n)
        except ValueError:
            raise SystemExit(f"{flag}: size for axis {axis!r} must be an "
                             f"integer (-1 = fill remaining devices), got "
                             f"{n.strip()!r}") from None
        if size == 0 or size < -1:
            raise SystemExit(f"{flag}: size for axis {axis!r} must be >= 1 "
                             "(or -1 to fill with the remaining devices)")
        shape[axis] = size
    if sum(1 for v in shape.values() if v == -1) > 1:
        raise SystemExit(f"{flag}: at most one axis may be -1")
    return shape


def parse_args(argv: Sequence[str] | None = None, workload: str = "",
               env: dict[str, str] | None = None) -> Config:
    args = build_parser(workload).parse_args(argv)
    dist = DistributedEnv.from_environ(env)
    if args.checkpoint_every and not args.checkpoint_dir:
        raise SystemExit("--checkpoint-every requires --checkpoint-dir "
                         "(silently dropping the cadence would be worse "
                         "than an error)")
    if args.checkpoint_every < 0:
        raise SystemExit(f"--checkpoint-every {args.checkpoint_every}: "
                         "must be >= 0")
    if args.remat_policy != "nothing" and not args.remat:
        raise SystemExit("--remat-policy requires --remat (a policy "
                         "without rematerialisation would be a silent "
                         "no-op)")
    if args.sentinel == "rollback" and not args.elastic:
        raise SystemExit("--sentinel rollback requires --elastic (rollback "
                         "restores the last checkpoint and replays with "
                         "the bad step skipped — that machinery IS the "
                         "elastic restart loop)")
    if args.sentinel != "off" and (args.sentinel_window < 1
                                   or args.sentinel_factor <= 1.0):
        raise SystemExit("--sentinel-window must be >= 1 and "
                         "--sentinel-factor > 1")
    if args.reshard and not (args.resume or args.elastic):
        raise SystemExit("--reshard requires --resume or --elastic (it "
                         "changes how an existing checkpoint is restored; "
                         "a fresh run has nothing to reshard)")
    if args.reshard and not args.checkpoint_dir:
        raise SystemExit("--reshard requires --checkpoint-dir (the "
                         "topology manifest lives next to the checkpoint)")
    if args.target_mesh and not args.reshard:
        raise SystemExit("--target-mesh requires --reshard (without the "
                         "resharding restore a mesh change would restore "
                         "garbage; use --mesh to shape a fresh run)")
    mesh_shape = parse_mesh_arg(args.mesh)
    if mesh_shape and args.nstages and \
            mesh_shape.get("stage", args.nstages) != args.nstages:
        raise SystemExit(f"--mesh stage={mesh_shape['stage']} conflicts "
                         f"with --nstages {args.nstages}; drop one (--mesh "
                         "wins over the mode-derived stage count)")
    if args.comm != "none":
        if args.zero != "fsdp":
            raise SystemExit("--comm quantizes the explicit FSDP param "
                             "all-gather / grad reduce-scatter; it requires "
                             "--zero fsdp (with no sharded params there is "
                             "no such collective to compress)")
        if args.grad_compress != "none":
            raise SystemExit("--comm and --grad-compress are mutually "
                             "exclusive: the FSDP dataflow has no pure "
                             "gradient all-reduce for --grad-compress to "
                             "act on")
        if args.grad_accum > 1:
            raise SystemExit("--comm does not compose with --grad-accum "
                             "(the accumulation loop re-gathers params per "
                             "microstep; quantizing those repeats is not "
                             "implemented)")
        bad = [a for a in ("model", "expert", "stage", "seq")
               if (mesh_shape or {}).get(a, 0) > 1]
        if bad:
            raise SystemExit(f"--comm requires a data/fsdp-only mesh; got "
                             f"{'/'.join(bad)} axes (the explicit FSDP "
                             "step owns the whole dataflow and does not "
                             "compose with model/expert/stage/seq "
                             "sharding)")
    if args.comm_overlap and args.comm == "none":
        raise SystemExit("--comm-overlap requires --comm bf16|int8 (it "
                         "selects the ring schedule for the explicit "
                         "collectives --comm turns on)")
    if args.plan_file and not args.autotune and not os.path.exists(args.plan_file):
        raise SystemExit(f"--plan {args.plan_file}: no such file (run "
                         "--autotune to produce one)")
    if args.obs_file and not args.obs:
        raise SystemExit("--obs-file requires --obs (the path names the "
                         "telemetry stream --obs records)")
    for flag, val in (("--obs-trace", args.obs_trace),
                      ("--obs-rotate-mb", args.obs_rotate_mb),
                      ("--obs-blackbox", args.obs_blackbox)):
        if val and not args.obs:
            raise SystemExit(f"{flag} requires --obs (it extends the "
                             "telemetry --obs turns on)")
    if args.obs_rotate_mb is not None and args.obs_rotate_mb <= 0:
        raise SystemExit(f"--obs-rotate-mb {args.obs_rotate_mb}: must "
                         "be > 0")
    if args.max_slots <= 0:
        raise SystemExit(f"--max-slots {args.max_slots}: must be >= 1 "
                         "(the engine's static batch dimension)")
    if args.kv_block_size < 1:
        raise SystemExit(f"--kv-block-size {args.kv_block_size}: must "
                         "be >= 1")
    if args.prefill_chunk < 1:
        raise SystemExit(f"--prefill-chunk {args.prefill_chunk}: must "
                         "be >= 1")
    if args.draft < 0:
        raise SystemExit(f"--draft {args.draft}: must be >= 0 (0 turns "
                         "speculative decoding off)")
    if args.draft and not args.paged:
        raise SystemExit("--draft requires --paged (speculation runs "
                         "inside the paged engine)")
    if args.spec_k < 1:
        raise SystemExit(f"--spec-k {args.spec_k}: must be >= 1")
    for flag, v in (("--slo-ttft-ms", args.slo_ttft_ms),
                    ("--slo-e2e-ms", args.slo_e2e_ms),
                    ("--serve-deadline-ms", args.serve_deadline_ms)):
        if v is not None and v <= 0:
            raise SystemExit(f"{flag} {v}: must be positive milliseconds")
    if args.serve_retries < 0:
        raise SystemExit(f"--serve-retries {args.serve_retries}: must be "
                         ">= 0 (0 = error a request on its first engine "
                         "fault)")
    if args.canary_slots < 0:
        raise SystemExit(f"--canary-slots {args.canary_slots}: must be "
                         ">= 0 (0 swaps verified weights without a "
                         "canary)")
    # the cap only binds when a reload watch will actually canary: the
    # default canary_slots must not invalidate small --max-slots runs
    if args.reload_watch and args.canary_slots >= args.max_slots:
        raise SystemExit(f"--canary-slots {args.canary_slots}: must be "
                         f"< --max-slots {args.max_slots} (at least one "
                         "slot must keep serving the stable weights)")
    for flag, v in (("--reload-watch", args.reload_watch),
                    ("--admission", args.admission)):
        if v and not args.serve:
            raise SystemExit(f"{flag} requires --serve (it extends the "
                             "post-train serving demo)")
    # serving quantization legality mirrors the engine constructors
    # (serve/quant.check_dtype + the PagedEngine-only int8 KV rule) so a
    # bad flag dies at parse time with the flag name, not inside a jit
    for flag, v in (("--kv-dtype", args.kv_dtype),
                    ("--weight-dtype", args.weight_dtype)):
        if v is not None and v not in ("bf16", "int8"):
            raise SystemExit(f"unknown {flag} {v!r}; choose bf16 or int8 "
                             "(or leave unset for full precision)")
    if args.replicas < 1:
        raise SystemExit(f"--replicas {args.replicas}: must be >= 1 "
                         "(1 = a single un-routed engine)")
    if args.replicas > 1 and not args.paged:
        raise SystemExit("--replicas > 1 requires --paged (the fleet "
                         "router's prefix-affinity placement and "
                         "zero-loss failover replay are built on the "
                         "paged engine's prefix index and ledger)")
    # the rebalance tier (evacuation + autoscaling) lives in the fleet
    # router: both flags are meaningless without a routed replica set
    if args.autoscale and args.replicas < 2:
        raise SystemExit("--autoscale requires --replicas > 1 (elastic "
                         "sizing grows/shrinks the fleet router's "
                         "replica set; a single un-routed engine has "
                         "nothing to scale)")
    if args.evacuate_on != "off" and args.replicas < 2:
        raise SystemExit(f"--evacuate-on {args.evacuate_on} requires "
                         "--replicas > 1 (a mid-request evacuation "
                         "needs a healthy peer to migrate the open "
                         "slots' committed KV to)")
    if args.priority_classes and not args.paged:
        raise SystemExit("--priority-classes requires --paged "
                         "(priority preemption spills and resumes "
                         "paged KV blocks)")
    if args.spill_dir and not args.priority_classes:
        raise SystemExit("--spill-dir requires --priority-classes "
                         "(spill files are only written when "
                         "preemption can fire)")
    if args.disagg and not args.paged:
        raise SystemExit("--disagg requires --paged (the prefill and "
                         "decode pools exchange committed paged-KV "
                         "blocks; the dense slot cache has no block "
                         "table to migrate)")
    if args.prefill_workers < 1:
        raise SystemExit(f"--prefill-workers {args.prefill_workers}: "
                         "must be >= 1")
    if args.prefill_workers != 1 and not args.disagg:
        raise SystemExit("--prefill-workers requires --disagg (worker "
                         "pools only exist in disaggregated serving)")
    if args.pool_elastic and not args.disagg:
        raise SystemExit("--pool-elastic requires --disagg (role "
                         "reassignment moves a worker between the "
                         "prefill and decode pools, which only exist "
                         "in disaggregated serving)")
    if args.disagg or args.migrate == "device":
        # these paths hard-require a device split, so resolve the
        # visible topology now and fail with the flag name instead of
        # deep inside engine construction (jax is imported lazily:
        # plain parses must not initialize a backend)
        import jax

        ndev = len(jax.local_devices())
        if args.migrate == "device" and ndev < 2:
            raise SystemExit("--migrate device: needs a second local "
                             f"device to park spilled KV on; only "
                             f"{ndev} visible — use --migrate host, or "
                             "run under a multi-device mesh (e.g. "
                             "XLA_FLAGS=--xla_force_host_platform_"
                             "device_count=2)")
        if args.disagg and ndev < 2:
            raise SystemExit("--disagg: disaggregated serving needs "
                             ">= 2 local devices (one per pool); only "
                             f"{ndev} visible — drop --disagg for the "
                             "unified paged engine, or run under a "
                             "multi-device mesh")
        if args.disagg and args.prefill_workers >= ndev:
            raise SystemExit(f"--prefill-workers {args.prefill_workers}"
                             f": the {ndev} visible devices must "
                             "partition into prefill + decode pools "
                             "with at least one decode worker — use "
                             f"1..{ndev - 1}")
    if args.publish_weights and not args.checkpoint_dir:
        raise SystemExit("--publish-weights requires --checkpoint-dir "
                         "(only verified checkpoint saves are "
                         "published for serving reload)")
    if args.kv_dtype == "int8" and not args.paged:
        raise SystemExit("--kv-dtype int8 requires --paged: int8 KV "
                         "stores per-position scales alongside the block "
                         "pools; the v1 slot table supports bf16 only "
                         "(the spec-decode draft pool inherits --kv-dtype "
                         "automatically)")
    return Config(
        num_layers=args.nlayers,
        size=args.size,
        epochs=args.epochs,
        batch_size=args.batch,
        device=Device(args.device),
        num_workers=args.nworkers,
        mode=Mode(args.mode),
        microbatch=args.pipeline,
        world_size=args.run,
        seed=args.seed,
        learning_rate=args.lr,
        dtype=args.dtype,
        num_stages=args.nstages,
        mesh_shape=mesh_shape,
        double_softmax=args.double_softmax,
        sync_in_local_data_mode=args.sync,
        zero=args.zero,
        grad_compress=args.grad_compress,
        comm=args.comm,
        comm_overlap=args.comm_overlap,
        grad_accum=args.grad_accum,
        dropout=args.dropout,
        remat=args.remat,
        remat_policy=args.remat_policy,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        resume=args.resume,
        profile_dir=args.profile_dir,
        data_dir=args.data_dir,
        packed_cache=args.packed_cache,
        image_size=args.image_size,
        stem_s2d=args.stem_s2d,
        attention=args.attention,
        attention_window=args.attention_window,
        optimizer=args.optimizer,
        generate_tokens=args.generate_tokens,
        serve=args.serve,
        max_slots=args.max_slots,
        prefill_buckets=parse_buckets_arg(args.prefill_buckets),
        paged=args.paged,
        kv_block_size=args.kv_block_size,
        prefill_chunk=args.prefill_chunk,
        draft=args.draft,
        spec_k=args.spec_k,
        slo_ttft_ms=args.slo_ttft_ms,
        slo_e2e_ms=args.slo_e2e_ms,
        serve_deadline_ms=args.serve_deadline_ms,
        serve_retries=args.serve_retries,
        reload_watch=args.reload_watch,
        canary_slots=args.canary_slots,
        admission=parse_admission_arg(args.admission),
        kv_dtype=args.kv_dtype,
        weight_dtype=args.weight_dtype,
        replicas=args.replicas,
        priority_classes=parse_priority_classes(args.priority_classes),
        spill_dir=args.spill_dir,
        autoscale=parse_autoscale_arg(args.autoscale),
        evacuate_on=args.evacuate_on,
        disagg=args.disagg,
        pool_elastic=args.pool_elastic,
        prefill_workers=args.prefill_workers,
        migrate=args.migrate,
        publish_weights=args.publish_weights,
        pos_embedding=args.pos_embedding,
        num_kv_heads=args.num_kv_heads,
        label_smoothing=args.label_smoothing,
        pipeline_schedule=args.pipeline_schedule,
        virtual_stages=args.virtual_stages,
        lr_schedule=args.lr_schedule,
        warmup_steps=args.warmup_steps,
        clip_norm=args.clip_norm,
        metrics_file=args.metrics_file,
        obs=args.obs,
        obs_file=args.obs_file,
        obs_trace=args.obs_trace,
        obs_rotate_mb=args.obs_rotate_mb,
        obs_blackbox=args.obs_blackbox,
        sentinel=args.sentinel,
        sentinel_window=args.sentinel_window,
        sentinel_factor=args.sentinel_factor,
        elastic=args.elastic,
        reshard=args.reshard,
        target_mesh=parse_mesh_arg(args.target_mesh, flag="--target-mesh"),
        heartbeat_dir=args.heartbeat_dir,
        heartbeat_timeout=args.heartbeat_timeout,
        autotune=args.autotune,
        plan_file=args.plan_file,
        distributed=dist,
    )
