"""Device-mesh construction: the TPU-native replacement for process groups.

The reference builds its topology out of `torch.distributed` process groups
with a 3-backend matrix (NCCL/Gloo/MPI, ``CNN/main.py:186-204``) plus
per-mode device lists (``CNN/main.py:143-154``).  On TPU the whole matrix
collapses into one object: a named :class:`jax.sharding.Mesh`.  Parallelism
modes are just different mesh shapes / sharding rules:

=============  =================================================
mode           mesh
=============  =================================================
sequential     1 device, trivial mesh
data           ``{"data": N}`` — batch sharded, params replicated
model          ``{"stage": S}`` — layer stages over devices
pipeline       ``{"stage": S}`` + microbatch schedule
hybrid         any combination, e.g. ``{"data": 4, "stage": 2}``
=============  =================================================

The canonical axis order is ``(data, fsdp, stage, model, seq, expert)``; axes
of size 1 are kept in the mesh so sharding rules never need to special-case
which axes exist.  XLA routes collectives over ICI within a slice and DCN
across slices based on device order, so we keep devices in their default
(topology-sorted) order.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from distributed_deep_learning_tpu.utils.config import MESH_AXES

# Canonical axis order (defined jax-free in utils/config.py so the CLI can
# validate --mesh at parse time).  `data` outermost (DCN-friendly: gradient
# all-reduce tolerates lower bandwidth), then fsdp (ZeRO-style param shard),
# then stage (pipeline), then model (tensor), then seq (context/ring-
# attention), then expert (MoE).  Order matters: ICI neighbours should serve
# the bandwidth-hungry inner axes.
AXES = MESH_AXES


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape over the canonical axes.

    Unspecified axes get size 1.  At most one axis may be -1 ("fill with all
    remaining devices").
    """

    data: int = -1
    fsdp: int = 1
    stage: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1

    @staticmethod
    def from_dict(shape: dict[str, int]) -> "MeshSpec":
        unknown = set(shape) - set(AXES)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; known: {AXES}")
        kw = {a: 1 for a in AXES}
        kw.update(shape)
        return MeshSpec(**kw)

    def sizes(self) -> tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Replace a single -1 with whatever devices remain."""
        sizes = list(self.sizes())
        fills = [i for i, s in enumerate(sizes) if s == -1]
        if len(fills) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = int(np.prod([s for s in sizes if s != -1]))
        if fills:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[fills[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} wants {fixed} devices, "
                f"have {n_devices}")
        return MeshSpec(**dict(zip(AXES, sizes)))


def _device_array(sizes: tuple[int, ...], devices) -> np.ndarray:
    """Arrange devices into the mesh shape, torus-aware on real TPUs.

    On multi-chip TPU, ``mesh_utils.create_device_mesh`` permutes devices so
    mesh axes land on physical ICI torus axes (nearest-neighbour collectives
    instead of topology-oblivious strides); across pod slices,
    ``create_hybrid_device_mesh`` puts the outermost (bandwidth-tolerant,
    see AXES ordering) axis on DCN.  Everything else — CPU test meshes,
    single chip — keeps the deterministic topology-sorted reshape.
    """
    devices = list(devices)
    if devices[0].platform == "tpu" and len(devices) > 1:
        from jax.experimental import mesh_utils

        slices = {getattr(d, "slice_index", 0) for d in devices}
        try:
            if len(slices) > 1:
                n_slices = len(slices)
                if sizes[0] % n_slices == 0:
                    per_slice = (sizes[0] // n_slices,) + sizes[1:]
                    dcn = (n_slices,) + (1,) * (len(sizes) - 1)
                    return mesh_utils.create_hybrid_device_mesh(
                        per_slice, dcn, devices=devices)
            else:
                return mesh_utils.create_device_mesh(sizes, devices=devices)
        except Exception:
            pass  # unusual topology: fall through to the plain reshape
    return np.asarray(devices).reshape(sizes)


def build_mesh(spec: MeshSpec | dict[str, int] | None = None,
               devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a named Mesh over `devices` (default: all of them)."""
    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec()
    if isinstance(spec, dict):
        spec = MeshSpec.from_dict(spec)
    spec = spec.resolve(len(devices))
    return Mesh(_device_array(spec.sizes(), devices), AXES)


def mesh_for_mode(mode: "str | None", n_stages: int | None = None,
                  devices: Sequence[jax.Device] | None = None,
                  explicit: dict[str, int] | None = None) -> Mesh:
    """Pick a mesh shape for a reference execution mode.

    Mirrors the reference's per-mode device-list construction
    (``CNN/main.py:143-154``) but as mesh shapes.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if explicit:
        return build_mesh(MeshSpec.from_dict(explicit), devices)
    mode = str(mode) if mode is not None else "sequential"
    if mode in ("model", "pipeline"):
        stages = n_stages or n
        if n % stages:
            raise ValueError(f"{n} devices not divisible into {stages} stages")
        return build_mesh({"stage": stages, "data": n // stages}, devices)
    if mode == "data":
        return build_mesh({"data": n}, devices)
    # sequential: single-device mesh (trivial shardings compile away)
    return build_mesh({"data": 1}, devices[:1])


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    n = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"data-parallel size {n}")
    return global_batch // n
