"""SPMD pipeline: numerical parity with sequential stage application, with
and without composed data parallelism, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_deep_learning_tpu.parallel.spmd_pipeline import (
    spmd_pipeline, stack_stage_params,
)
from distributed_deep_learning_tpu.runtime.mesh import build_mesh


def _stage_params(key, n_stages, width):
    keys = jax.random.split(key, n_stages)
    return [
        {"w": jax.random.normal(k, (width, width)) / np.sqrt(width),
         "b": jnp.zeros((width,))}
        for k in keys
    ]


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _sequential(params_list, x):
    for p in params_list:
        x = _stage_fn(p, x)
    return x


@pytest.fixture(scope="module")
def mesh_stage4():
    return build_mesh({"stage": 4, "data": 2})


def _place(params_list, mesh):
    stacked = stack_stage_params(params_list)
    return jax.device_put(stacked, NamedSharding(mesh, P("stage")))


def test_pipeline_matches_sequential(mesh_stage4):
    width, B = 16, 32
    params_list = _stage_params(jax.random.key(0), 4, width)
    x = jax.random.normal(jax.random.key(1), (B, width))
    expected = _sequential(params_list, x)

    stacked = _place(params_list, mesh_stage4)
    got = jax.jit(lambda p, v: spmd_pipeline(
        _stage_fn, p, v, mesh=mesh_stage4, microbatch_size=8))(stacked, x)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got),
                               rtol=1e-5, atol=1e-6)


def test_single_microbatch_is_model_mode(mesh_stage4):
    width, B = 8, 8
    params_list = _stage_params(jax.random.key(2), 4, width)
    x = jax.random.normal(jax.random.key(3), (B, width))
    stacked = _place(params_list, mesh_stage4)
    got = spmd_pipeline(_stage_fn, stacked, x, mesh=mesh_stage4,
                        microbatch_size=B)  # M=1: plain staged walk
    np.testing.assert_allclose(np.asarray(_sequential(params_list, x)),
                               np.asarray(got), rtol=1e-5, atol=1e-6)


def test_pipeline_backward_matches_sequential(mesh_stage4):
    width, B = 8, 16
    params_list = _stage_params(jax.random.key(4), 4, width)
    x = jax.random.normal(jax.random.key(5), (B, width))

    def loss_seq(plist):
        return jnp.sum(_sequential(plist, x) ** 2)

    def loss_pipe(stacked):
        out = spmd_pipeline(_stage_fn, stacked, x, mesh=mesh_stage4,
                            microbatch_size=4)
        return jnp.sum(out ** 2)

    g_seq = jax.grad(loss_seq)(params_list)
    stacked = _place(params_list, mesh_stage4)
    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_seq_stacked = stack_stage_params(g_seq)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_seq_stacked, g_pipe)


def test_indivisible_microbatch_raises(mesh_stage4):
    params_list = _stage_params(jax.random.key(6), 4, 8)
    stacked = _place(params_list, mesh_stage4)
    x = jnp.zeros((10, 8))
    with pytest.raises(ValueError):
        spmd_pipeline(_stage_fn, stacked, x, mesh=mesh_stage4, microbatch_size=4)
