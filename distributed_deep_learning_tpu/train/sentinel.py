"""On-device anomaly sentinel: detect and contain bad updates in-step.

The failure this closes is *silent numerical poisoning*: one batch with a
NaN (a corrupt record, a bit-flipped host buffer) or one pathological
gradient spike updates the params, the checkpointer then immortalises the
poisoned state, and elastic recovery faithfully restores it — the recovery
chain amplifies the fault instead of containing it.  Production pjit/TPU
runs treat loss spikes as routine events, not exceptions ("Scalable
Training of Language Models using JAX pjit and TPUv4", PAPERS.md §skipping
anomalous batches), so the defence has to live on the hot path.

Mechanism: the sentinel runs INSIDE the jitted train step.  After the
backward it computes the global gradient norm, checks loss and grad-norm
finiteness, and compares both against exponential running means kept in a
four-scalar :class:`SentinelState` threaded through the step.  When the
step is anomalous the already-computed update is *discarded on device* —
every state leaf takes a ``jnp.where(anomaly, old, new)`` select, so the
params/optimizer/step/rng-stream are bit-identical to never having trained
that batch.  No host synchronisation is added: the verdict rides the
per-step metrics dict the loop already keeps on device.

Policies (:class:`SentinelConfig.policy`) decide what the HOST does with a
detected anomaly — the device-side containment above happens under all of
them, so params are safe even before the host notices:

``skip``
    Nothing: the batch's update is dropped, training continues.  Skips are
    counted in the phase totals (``anomaly`` metric) and logged.
``rollback``
    The loop raises :class:`AnomalyError`;
    :func:`..train.elastic.fit_with_recovery` restores the last verified
    checkpoint and replays the epoch with the offending global step in its
    skip set — used when a bad batch should also invalidate optimizer-state
    history, or under chaos drills that corrupt state outside the step.
``halt``
    The loop raises :class:`AnomalyError` and nothing catches it: the run
    stops with the state clean as of the last good step.

Detection latency is at most one step: the loop checks the PREVIOUS step's
verdict right after dispatching the next one (the scalar is already on its
way to the host), so rollback/halt fire within a step of the anomaly while
the device pipeline stays busy.
"""

from __future__ import annotations

import dataclasses

import flax.struct
import jax
import jax.numpy as jnp
import optax

#: anomaly verdict codes carried in the per-step ``anomaly_code`` metric
OK, NONFINITE, GRAD_SPIKE, LOSS_SPIKE = 0, 1, 2, 3

_CODE_NAMES = {NONFINITE: "non-finite loss/grad",
               GRAD_SPIKE: "gradient-norm spike",
               LOSS_SPIKE: "loss spike"}

POLICIES = ("skip", "rollback", "halt")


class AnomalyError(RuntimeError):
    """Raised by the loop when the sentinel policy is rollback/halt.

    The offending update was already discarded on device — the state the
    loop holds is clean as of the last good step; ``global_step`` names
    the data window to skip on replay."""

    def __init__(self, global_step: int, policy: str, code: int,
                 detail: str = ""):
        self.global_step = int(global_step)
        self.policy = policy
        self.code = int(code)
        what = _CODE_NAMES.get(self.code, "anomaly")
        super().__init__(
            f"anomaly sentinel: {what} at global train step {global_step} "
            f"(policy={policy}{'; ' + detail if detail else ''})")


@dataclasses.dataclass(frozen=True)
class SentinelConfig:
    """Static sentinel configuration (baked into the compiled step).

    ``window`` is the EMA horizon in steps for the running grad-norm/loss
    means; ``spike_factor``/``loss_spike_factor`` are the multiples of
    those means that count as a spike; the first ``warmup_steps`` clean
    steps only feed the means (no spike verdicts — the very first steps of
    a run legitimately have wild norms).  Finiteness is always checked,
    warmup included."""

    policy: str = "skip"
    window: int = 32
    spike_factor: float = 10.0
    loss_spike_factor: float = 10.0
    warmup_steps: int = 8

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(f"sentinel policy {self.policy!r}: choose from "
                             f"{POLICIES}")
        if self.window < 1 or self.warmup_steps < 1:
            raise ValueError("sentinel window and warmup_steps must be >= 1")
        if self.spike_factor <= 1.0 or self.loss_spike_factor <= 1.0:
            raise ValueError("sentinel spike factors must be > 1")


@flax.struct.dataclass
class SentinelState:
    """Four device scalars threaded through the jitted step."""

    grad_ema: jax.Array   # running mean of the global grad norm
    loss_ema: jax.Array   # running mean of the batch loss
    count: jax.Array      # clean steps observed (drives warmup)
    anomalies: jax.Array  # cumulative anomalous steps (contained)


def init_sentinel() -> SentinelState:
    # four DISTINCT arrays: sharing one zeros() buffer across fields would
    # donate the same buffer twice in the jitted step (donate_argnums=(0,))
    return SentinelState(grad_ema=jnp.zeros((), jnp.float32),
                         loss_ema=jnp.zeros((), jnp.float32),
                         count=jnp.zeros((), jnp.int32),
                         anomalies=jnp.zeros((), jnp.int32))


def attach_sentinel(state):
    """Return ``state`` with a fresh :class:`SentinelState` attached.

    Must run BEFORE sharding specs are derived from the state (the spec
    builders map the sentinel scalars to replicated specs)."""
    return state.replace(sentinel=init_sentinel())


def guarded_update(state, grads, new_ms, metrics, cfg: SentinelConfig):
    """The sentinel step body: verdict, containment, stats update.

    Runs inside the jitted train step.  Returns ``(new_state, metrics)``
    where ``metrics`` gains ``anomaly`` (0/1), ``anomaly_code`` and
    ``grad_norm``, and the task metrics of an anomalous step are zeroed —
    phase totals then equal those of a run that never saw the bad batch
    (the bit-identical containment contract ``tests/test_chaos.py``
    asserts)."""
    sen = state.sentinel
    if sen is None:
        raise ValueError("sentinel config given but state has no sentinel "
                         "state — build the state via attach_sentinel()")
    gnorm = optax.global_norm(grads)
    loss = metrics["loss"]
    finite = jnp.isfinite(gnorm) & jnp.isfinite(loss)
    warm = sen.count >= cfg.warmup_steps
    g_spike = warm & (gnorm > cfg.spike_factor * sen.grad_ema)
    l_spike = warm & (loss > cfg.loss_spike_factor * sen.loss_ema)
    anomaly = ~finite | g_spike | l_spike
    code = jnp.where(~finite, NONFINITE,
                     jnp.where(g_spike, GRAD_SPIKE,
                               jnp.where(l_spike, LOSS_SPIKE, OK)))

    candidate = state.apply_gradients(grads, model_state=new_ms)

    def contain(new, old):
        return jax.tree.map(lambda n, o: jnp.where(anomaly, o, n), new, old)

    # EMA over clean steps only (an anomalous norm must not inflate the
    # very threshold that flagged it); the first clean step seeds the mean
    alpha = 1.0 / cfg.window
    first = sen.count == 0

    def ema(prev, x):
        seeded = jnp.where(first, x, (1.0 - alpha) * prev + alpha * x)
        return jnp.where(anomaly, prev, seeded)

    new_sen = SentinelState(
        grad_ema=ema(sen.grad_ema, gnorm),
        loss_ema=ema(sen.loss_ema, loss),
        count=sen.count + jnp.where(anomaly, 0, 1).astype(jnp.int32),
        anomalies=sen.anomalies + anomaly.astype(jnp.int32))

    new_state = candidate.replace(
        step=jnp.where(anomaly, state.step, candidate.step),
        params=contain(candidate.params, state.params),
        model_state=contain(candidate.model_state, state.model_state),
        opt_state=contain(candidate.opt_state, state.opt_state),
        sentinel=new_sen)

    # anomalous steps contribute nothing to the phase totals — neither the
    # (possibly NaN) loss nor the sample count
    out = {k: jnp.where(anomaly, jnp.zeros_like(v), v)
           for k, v in metrics.items()}
    out["anomaly"] = anomaly.astype(jnp.float32)
    out["anomaly_code"] = code.astype(jnp.float32)
    out["grad_norm"] = jnp.where(finite, gnorm, 0.0)
    return new_state, out
