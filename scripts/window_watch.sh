#!/bin/bash
# Probe the tunneled TPU every ~4 min; on the first healthy probe,
# harvest in safety order: (1) tpu_validation.py — each section runs in
# its own watchdogged subprocess and logs incrementally, so a window
# that dies mid-harvest still keeps every completed section (and its
# compiles land in .jax_cache); (2) the orchestrated bench on the now-
# warm cache, whose full section set then fits the first 720s attempt.
cd /root/repo
for i in $(seq 1 200); do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float(jnp.sum(x@x)) > 0" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) probe OK — harvesting" >> bench_r5_harvest.log
    python scripts/tpu_validation.py >> bench_r5_harvest.log 2>&1
    echo "validation rc=$?" >> bench_r5_harvest.log
    python bench.py >> bench_r5_harvest.log 2>&1
    echo "bench rc=$?" >> bench_r5_harvest.log
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) probe $i dead" >> bench_r5_harvest.log
  sleep 240
done
exit 1
