"""Rolling-window aggregation: live signals over the trailing N seconds.

The gen-1 histograms (:mod:`..obs.metrics`) accumulate forever — perfect
for end-of-run rollups, useless for "what is the queue doing *right
now*".  A :class:`WindowedHistogram` is a ring of time-sliced
:class:`~..obs.metrics.Histogram` buckets: observations land in the
slice covering the current clock reading, reads merge the slices inside
the trailing window (EXACT merge — every slice shares the same bucket
bounds by construction), and slices that age out are lazily zeroed the
next time their ring position comes around.  Cost: ``observe`` is the
same single ``bisect`` as the base histogram plus one integer epoch
check; a read is a bounded sum over ``slices`` small count arrays.

:class:`LiveSignals` bundles the four signals the ROADMAP fleet tier
(router / autoscaler) consumes — p50/p99 TTFT, inter-token latency,
queue depth, and slot occupancy over the trailing window — behind one
object the serve engines feed and periodically flush as ``obs_window``
events.  Clock injection makes every number deterministic under test.
"""

from __future__ import annotations

import time

from .metrics import Histogram

__all__ = ["WindowedHistogram", "LiveSignals"]


class WindowedHistogram:
    """A ring of ``slices`` time-sliced histograms covering the trailing
    ``window_s`` seconds.

    Each slice covers ``window_s / slices`` seconds of clock time and is
    keyed by its integer epoch (``now // slice_s``); a slice whose
    stored epoch is stale is reset before reuse, so neither observes nor
    reads ever pay for wall-clock gaps (an idle engine costs nothing).
    """

    def __init__(self, window_s: float = 10.0, slices: int = 10, *,
                 lo: float = 1e-5, hi: float = 100.0, growth: float = 1.25,
                 clock=time.monotonic) -> None:
        if window_s <= 0 or slices < 1:
            raise ValueError(f"bad window window_s={window_s} "
                             f"slices={slices}")
        self.window_s = float(window_s)
        self.n = int(slices)
        self.slice_s = self.window_s / self.n
        self.clock = clock
        self._lo, self._hi, self._growth = lo, hi, growth
        self._hists = [Histogram(lo=lo, hi=hi, growth=growth)
                       for _ in range(self.n)]
        self._epochs = [-1] * self.n

    def _slot(self, now: float) -> int:
        """The ring index for ``now``, with its slice reset if stale."""
        epoch = int(now // self.slice_s)
        i = epoch % self.n
        if self._epochs[i] != epoch:
            self._hists[i] = Histogram(lo=self._lo, hi=self._hi,
                                       growth=self._growth)
            self._epochs[i] = epoch
        return i

    def observe(self, v: float, now: float | None = None) -> None:
        if now is None:
            now = self.clock()
        self._hists[self._slot(now)].observe(v)

    def merged(self, now: float | None = None) -> Histogram:
        """The trailing window as ONE histogram (exact bucket-wise sum
        of the live slices; identical bounds by construction)."""
        if now is None:
            now = self.clock()
        epoch = int(now // self.slice_s)
        out = Histogram(lo=self._lo, hi=self._hi, growth=self._growth)
        for i in range(self.n):
            if not (epoch - self.n < self._epochs[i] <= epoch):
                continue  # stale (or never-written) slice: aged out
            h = self._hists[i]
            if not h.count:
                continue
            for j, c in enumerate(h.counts):
                out.counts[j] += c
            out.count += h.count
            out.sum += h.sum
            out.min = min(out.min, h.min)
            out.max = max(out.max, h.max)
        return out

    def percentile(self, p: float, now: float | None = None) -> float:
        return self.merged(now).percentile(p)

    def count(self, now: float | None = None) -> int:
        return self.merged(now).count

    def rate(self, now: float | None = None) -> float:
        """Events per second over the trailing window."""
        return self.count(now) / self.window_s


class LiveSignals:
    """The serve-side live-signal bundle: TTFT, inter-token latency,
    queue depth, and slot occupancy over the trailing window.

    The engine calls :meth:`observe_ttft` / :meth:`observe_itl` as
    latencies materialise and :meth:`sample` once per tick with the
    current queue depth and occupancy; :meth:`signals` renders the
    admission/autoscale view the fleet tier reads.  All four windows
    share one injected clock.
    """

    def __init__(self, window_s: float = 10.0, slices: int = 10, *,
                 clock=time.monotonic) -> None:
        self.window_s = float(window_s)
        self.clock = clock
        kw = dict(window_s=window_s, slices=slices, clock=clock)
        self.ttft = WindowedHistogram(**kw)
        self.itl = WindowedHistogram(**kw)
        # depth/occupancy are small integers: finer growth + a 0.5 floor
        # keeps the quantile error below one slot
        self.queue = WindowedHistogram(lo=0.5, hi=65536.0, growth=1.25,
                                       window_s=window_s, slices=slices,
                                       clock=clock)
        self.occupancy = WindowedHistogram(lo=0.5, hi=65536.0, growth=1.25,
                                           window_s=window_s, slices=slices,
                                           clock=clock)
        self._last_queue = 0.0
        self._last_occ = 0.0

    def observe_ttft(self, seconds: float, now: float | None = None) -> None:
        self.ttft.observe(seconds, now)

    def observe_itl(self, seconds: float, now: float | None = None) -> None:
        self.itl.observe(seconds, now)

    def sample(self, queue_depth: float, occupancy: float,
               now: float | None = None) -> None:
        """One per-tick sample of the instantaneous gauges."""
        self._last_queue = float(queue_depth)
        self._last_occ = float(occupancy)
        self.queue.observe(queue_depth, now)
        self.occupancy.observe(occupancy, now)

    def signals(self, now: float | None = None) -> dict:
        """The live view: percentiles over the trailing window plus the
        instantaneous last samples."""
        if now is None:
            now = self.clock()
        ttft = self.ttft.merged(now)
        itl = self.itl.merged(now)
        q = self.queue.merged(now)
        occ = self.occupancy.merged(now)
        return {
            "window_s": self.window_s,
            "ttft_p50_s": ttft.percentile(50),
            "ttft_p99_s": ttft.percentile(99),
            "ttft_count": ttft.count,
            "itl_p50_s": itl.percentile(50),
            "itl_p99_s": itl.percentile(99),
            "itl_count": itl.count,
            "queue_depth_p50": q.percentile(50),
            "queue_depth_max": q.max if q.count else 0.0,
            "queue_depth_last": self._last_queue,
            "occupancy_mean": occ.mean,
            "occupancy_last": self._last_occ,
            "request_rate_per_s": ttft.count / self.window_s,
            "token_rate_per_s": itl.count / self.window_s,
        }
