"""Versioned plan artifacts: the search result as a replayable JSON file.

A plan is only meaningful for the (workload, model geometry, topology) it
was searched on — replaying a gpt/8-device plan on an mlp/1-device run
would silently train the wrong configuration.  So every artifact carries a
``key``: a hash over exactly those inputs, recomputed at load time and
rejected on mismatch (:class:`StalePlanError`), the same way the packed
sample cache rejects a stale source.  ``plan_hash`` fingerprints the plan
itself so bench records can track plan churn across commits.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any

from distributed_deep_learning_tpu.tune.space import Plan
from distributed_deep_learning_tpu.utils.config import Config

#: v2: Plan grew the ``comm``/``comm_overlap`` axes (quantized +
#: ring-overlapped FSDP collectives) — v1 artifacts predate them and
#: must re-search, not silently replay without the new knobs
#: v3: Plan grew the serving-surface axes ``paged``/``kv_dtype``/
#: ``weight_dtype`` (quantized serving hot path) — v2 artifacts lack
#: them and must re-search for the same reason
PLAN_SCHEMA_VERSION = 3


class StalePlanError(ValueError):
    """The artifact's schema version or key does not match this run."""


def _digest(payload: Any) -> str:
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def plan_key(workload: str, config: Config, n_devices: int,
             platform: str = "", device_kind: str = "") -> str:
    """Hash of what a plan is valid FOR: workload + model geometry +
    topology.  Deliberately excludes every knob the search itself sets
    (mesh, remat, zero, ...) — those live in the plan."""
    return _digest({
        "workload": workload,
        "num_layers": config.num_layers,
        "size": config.size,
        "batch_size": config.batch_size,
        "n_devices": n_devices,
        "platform": platform,
        "device_kind": device_kind,
    })


def plan_hash(plan: Plan) -> str:
    """Stable fingerprint of the plan itself (for churn tracking)."""
    return _digest(plan.to_dict())


def save_plan(path: str, plan: Plan, *, key: str, workload: str,
              topology: dict[str, Any] | None = None,
              search: dict[str, Any] | None = None) -> dict[str, Any]:
    """Write the artifact; returns the record written."""
    record = {
        "version": PLAN_SCHEMA_VERSION,
        "key": key,
        "workload": workload,
        "plan": plan.to_dict(),
        "plan_hash": plan_hash(plan),
        "topology": topology or {},
        # search telemetry (trial scores, wall time) — informational only,
        # never part of the key or hash
        "search": search or {},
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return record


def load_plan(path: str, expected_key: str | None = None
              ) -> tuple[Plan, dict[str, Any]]:
    """Read and verify an artifact; returns (plan, full record).

    Raises :class:`StalePlanError` when the schema version is foreign or
    ``expected_key`` (this run's recomputed key) doesn't match — a plan
    searched for a different workload/geometry/topology must not apply.
    """
    with open(path) as f:
        record = json.load(f)
    version = record.get("version")
    if version != PLAN_SCHEMA_VERSION:
        raise StalePlanError(
            f"plan {path}: schema version {version!r} != "
            f"{PLAN_SCHEMA_VERSION} (re-run --autotune)")
    if expected_key is not None and record.get("key") != expected_key:
        raise StalePlanError(
            f"plan {path}: key {record.get('key')!r} was searched for a "
            f"different workload/geometry/topology (this run's key: "
            f"{expected_key!r}); re-run --autotune")
    plan = Plan.from_dict(record["plan"])
    stored = record.get("plan_hash")
    if stored and stored != plan_hash(plan):
        raise StalePlanError(f"plan {path}: plan_hash {stored!r} does not "
                             "match the stored plan (artifact edited?)")
    return plan, record
