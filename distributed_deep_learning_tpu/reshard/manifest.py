"""Topology manifest: how a checkpoint's bytes were laid out across devices.

The integrity sidecar (:mod:`..utils.checkpoint`) already records *what* was
saved — per-leaf CRC32, shape, dtype, finiteness.  This module records *how*:
the mesh axis names and sizes, the device count, and every leaf's
``PartitionSpec``, as a plain-JSON block embedded in the same sidecar.  A
restore on a different topology reads it to decide whether the checkpoint
can be taken as-is (same topology), must be resharded (different topology),
or predates topology manifests entirely (legacy — assume same topology,
warn, never quarantine).

Everything here is metadata-only: :func:`capture` walks a pytree's sharding
attributes without touching array bytes, so writing the manifest costs
microseconds regardless of model size.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

TOPOLOGY_FORMAT = 1


def _canonical_entries(spec) -> tuple:
    """A PartitionSpec's entries in canonical form: tuples for multi-axis
    entries, trailing ``None`` padding stripped (``P("data", None)`` and
    ``P("data")`` describe the same placement but differ as raw tuples)."""
    out = [tuple(e) if isinstance(e, (list, tuple)) else e for e in spec]
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def spec_to_json(spec) -> list:
    """PartitionSpec -> JSON-safe entry list (axis name, axis-name list, or
    null for an unsharded dimension)."""
    return [list(e) if isinstance(e, tuple) else e
            for e in _canonical_entries(spec)]


def spec_from_json(entries) -> Any:
    """Inverse of :func:`spec_to_json`."""
    from jax.sharding import PartitionSpec as P

    return P(*[tuple(e) if isinstance(e, list) else e
               for e in (entries or [])])


@dataclasses.dataclass(frozen=True)
class Topology:
    """The placement fingerprint of one saved state.

    ``mesh_shape`` keeps every axis (including size-1 ones) in mesh order so
    the manifest is a faithful record; comparisons normalise size-1 axes
    away — a ``data=8`` mesh and a ``data=8, fsdp=1`` mesh place bytes
    identically.  ``leaf_specs`` maps ``jax.tree_util.keystr`` paths (the
    same keys as the integrity manifest's ``leaves``) to canonical
    PartitionSpec entry tuples.
    """

    mesh_shape: tuple[tuple[str, int], ...]
    n_devices: int
    leaf_specs: dict[str, tuple]

    def mesh_dict(self) -> dict[str, int]:
        return dict(self.mesh_shape)

    def normalized_mesh(self) -> tuple[tuple[str, int], ...]:
        out = tuple((a, s) for a, s in self.mesh_shape if s != 1)
        return out if out else (("data", 1),)

    def describe(self) -> str:
        mesh = ",".join(f"{a}={s}" for a, s in self.normalized_mesh())
        return f"mesh[{mesh}]x{self.n_devices}dev"

    def to_json(self) -> dict:
        return {
            "format": TOPOLOGY_FORMAT,
            "mesh": {a: s for a, s in self.mesh_shape},
            "n_devices": self.n_devices,
            "leaf_specs": {k: [list(e) if isinstance(e, tuple) else e
                               for e in v]
                           for k, v in self.leaf_specs.items()},
        }

    @staticmethod
    def from_json(payload) -> "Topology | None":
        """Parse a manifest's ``topology`` block; ``None`` for anything
        missing or malformed (the caller treats that as legacy)."""
        try:
            mesh = tuple((str(a), int(s))
                         for a, s in payload["mesh"].items())
            specs = {str(k): _canonical_entries(spec_from_json(v))
                     for k, v in payload.get("leaf_specs", {}).items()}
            return Topology(mesh_shape=mesh,
                            n_devices=int(payload["n_devices"]),
                            leaf_specs=specs)
        except (TypeError, KeyError, ValueError, AttributeError):
            return None


def same_topology(a: Topology | None, b: Topology | None) -> bool:
    """True when two topologies place bytes identically: same device count,
    same non-trivial mesh axes, same per-leaf specs."""
    if a is None or b is None:
        return False
    return (a.n_devices == b.n_devices
            and a.normalized_mesh() == b.normalized_mesh()
            and a.leaf_specs == b.leaf_specs)


def _mesh_of(sharding) -> tuple[tuple[tuple[str, int], ...], int] | None:
    import jax

    if isinstance(sharding, jax.sharding.NamedSharding):
        shape = tuple((str(a), int(s)) for a, s in sharding.mesh.shape.items())
        return shape, int(sharding.mesh.devices.size)
    return None


def capture(tree) -> Topology:
    """Fingerprint a *placed* pytree (the ``_as_pytree`` view of a
    TrainState): mesh from the first NamedSharding leaf, per-leaf specs
    keyed exactly like the integrity manifest.  Leaves without a
    NamedSharding (host scalars, single-device runs) record as fully
    replicated ``P()``."""
    import jax
    from jax.sharding import PartitionSpec as P

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    mesh_shape, n_devices = None, None
    leaf_specs: dict[str, tuple] = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        sharding = getattr(leaf, "sharding", None)
        found = _mesh_of(sharding)
        if found is not None:
            leaf_specs[key] = _canonical_entries(sharding.spec)
            if mesh_shape is None:
                mesh_shape, n_devices = found
        else:
            leaf_specs[key] = _canonical_entries(P())
    if mesh_shape is None:
        mesh_shape, n_devices = (("data", 1),), 1
    if not n_devices:  # pragma: no cover - defensive
        n_devices = max(1, math.prod(s for _, s in mesh_shape))
    return Topology(mesh_shape=mesh_shape, n_devices=n_devices,
                    leaf_specs=leaf_specs)


def of_placement(mesh, shardings_tree) -> Topology:
    """Fingerprint a *target* placement: a pytree of shardings (shaped like
    the state's ``_as_pytree`` view) on ``mesh``.  This is what the restore
    path compares a saved :class:`Topology` against."""
    import jax
    from jax.sharding import PartitionSpec as P

    flat, _ = jax.tree_util.tree_flatten_with_path(
        shardings_tree, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
    leaf_specs = {}
    for path, sharding in flat:
        key = jax.tree_util.keystr(path)
        if isinstance(sharding, jax.sharding.NamedSharding):
            leaf_specs[key] = _canonical_entries(sharding.spec)
        else:
            leaf_specs[key] = _canonical_entries(P())
    mesh_shape = tuple((str(a), int(s)) for a, s in mesh.shape.items())
    return Topology(mesh_shape=mesh_shape,
                    n_devices=int(mesh.devices.size),
                    leaf_specs=leaf_specs)
