"""Fused linear+cross-entropy vs the materialised logits path: values,
gradients, padding semantics — the (N, V) logit matrix never exists."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_deep_learning_tpu.ops.fused_ce import (
    fused_linear_cross_entropy)


def _reference(h, table, targets, ignore_id=0):
    logits = h.astype(jnp.float32) @ table.astype(jnp.float32).T
    per = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    valid = targets != ignore_id
    return jnp.sum(jnp.where(valid, per, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)


def _data(N=24, d=16, V=64, seed=0, pad_tail=4):
    ks = jax.random.split(jax.random.key(seed), 3)
    h = jax.random.normal(ks[0], (N, d))
    table = jax.random.normal(ks[1], (V, d)) * 0.1
    targets = jax.random.randint(ks[2], (N,), 1, V)
    targets = targets.at[-pad_tail:].set(0)
    return h, table, targets


def test_matches_reference_loss():
    h, table, targets = _data()
    got = fused_linear_cross_entropy(h, table, targets, 0, 16)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_matches_with_single_block():
    h, table, targets = _data(seed=1)
    got = fused_linear_cross_entropy(h, table, targets, 0, 64)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_gradients_match_reference():
    h, table, targets = _data(seed=2)

    g_fused = jax.grad(
        lambda h, w: fused_linear_cross_entropy(h, w, targets, 0, 16),
        argnums=(0, 1))(h, table)
    g_ref = jax.grad(lambda h, w: _reference(h, w, targets),
                     argnums=(0, 1))(h, table)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_batched_sequence_shape():
    """(B, T, d) activations + (B, T) targets — the LM calling shape."""
    h, table, targets = _data(N=32, seed=3)
    h3 = h.reshape(4, 8, -1)
    t3 = targets.reshape(4, 8)
    got = fused_linear_cross_entropy(h3, table, t3, 0, 16)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_all_padding_is_finite():
    h, table, _ = _data(seed=4)
    targets = jnp.zeros((24,), jnp.int32)  # everything ignored
    got = fused_linear_cross_entropy(h, table, targets, 0, 16)
    assert float(got) == 0.0
    g = jax.grad(lambda h: fused_linear_cross_entropy(
        h, table, targets, 0, 16))(h)
    assert np.isfinite(np.asarray(g)).all()
    np.testing.assert_allclose(np.asarray(g), 0.0, atol=1e-8)


def test_indivisible_block_raises():
    h, table, targets = _data()
    with pytest.raises(ValueError, match="divisible"):
        fused_linear_cross_entropy(h, table, targets, 0, 48)


def test_bf16_activations():
    h, table, targets = _data(seed=5)
    got = fused_linear_cross_entropy(h.astype(jnp.bfloat16), table,
                                     targets, 0, 16)
    want = _reference(h, table, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=2e-2)


def test_under_jit_and_grad_jit():
    h, table, targets = _data(seed=6)
    f = jax.jit(lambda h, w: fused_linear_cross_entropy(h, w, targets, 0, 16))
    g = jax.jit(jax.grad(f, argnums=(0, 1)))
    np.testing.assert_allclose(float(f(h, table)),
                               float(_reference(h, table, targets)),
                               rtol=1e-5)
    for a, b in zip(g(h, table),
                    jax.grad(lambda h, w: _reference(h, w, targets),
                             argnums=(0, 1))(h, table)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6)
