from distributed_deep_learning_tpu.models.mlp import MLP, mlp_layer_sequence  # noqa: F401
from distributed_deep_learning_tpu.models.densenet import (  # noqa: F401
    DenseNet, densenet_layer_sequence,
)
from distributed_deep_learning_tpu.models.cnn_lstm import (  # noqa: F401
    CNNLSTM, cnn_lstm_layer_sequence,
)
