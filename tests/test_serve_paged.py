"""Paged serving engine (ISSUE 9): paged KV + prefix reuse + chunked
prefill + speculative decoding, under the same two load-bearing
guarantees as the v1 engine — compile-once and bit-identical greedy
outputs against ``generate()`` — plus the new ones this generation
adds:

* prefix reuse measurably reduces prefill compute WITHOUT changing one
  output token (shared blocks are referenced, the last prompt token is
  always recomputed, copy-on-write isolates divergence);
* chunked prefill bounds decode stalls: live streams decode EVERY tick
  while a long prompt lands chunk by chunk (timeline-asserted);
* speculative decoding preserves exact greedy parity while the target
  runs fewer forwards (verify replaces plain decode: ``decode==0``,
  ``verify==1``, ``draft==1`` compile counts);
* a burst of long prompts cannot starve a queued short request
  (round-robin chunk budget → bounded wait — the fairness regression).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_deep_learning_tpu.models.transformer import (CausalLM,
                                                              generate)
from distributed_deep_learning_tpu.serve.engine import PagedEngine
from distributed_deep_learning_tpu.serve.load import (LoadSpec, make_load,
                                                      slo_report)
from distributed_deep_learning_tpu.serve.paged import (TRASH, BlockManager,
                                                       chain_hash)
from distributed_deep_learning_tpu.serve.prefill import (plan_chunks,
                                                         write_targets)
from distributed_deep_learning_tpu.serve.scheduler import Request
from distributed_deep_learning_tpu.serve.spec import (greedy_accept,
                                                      truncated_draft)
from distributed_deep_learning_tpu.utils.config import parse_args

MODEL = dict(vocab_size=61, num_layers=2, d_model=32, num_heads=4,
             mlp_dim=64, max_len=48)


@functools.lru_cache(maxsize=None)
def _shared(**kw):
    model = CausalLM(**{**MODEL, **kw})
    toks = jnp.ones((1, 4), jnp.int32)
    return model, model.init(jax.random.key(1), toks)["params"]


def _engine(**kw):
    model, params = _shared()
    kw.setdefault("max_slots", 3)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return PagedEngine(model, params, **kw)


def _trace(seed=0, n=6, max_new=(1, 10), plens=(3, 20), stagger=3):
    rng = np.random.default_rng(seed)
    reqs, tick = [], 0
    for uid in range(n):
        p = int(rng.integers(*plens))
        reqs.append(Request(uid, rng.integers(1, 61, p).astype(np.int32),
                            int(rng.integers(*max_new)),
                            arrival_tick=tick))
        tick += int(rng.integers(0, stagger + 1))
    return reqs


def _check_parity(out, reqs, label="", **model_kw):
    model, params = _shared(**model_kw)
    for r in reqs:
        ref = generate(model, params, jnp.asarray(r.prompt)[None],
                       max_new_tokens=r.max_new_tokens)
        np.testing.assert_array_equal(out["results"][r.uid],
                                      np.asarray(ref)[0],
                                      err_msg=f"{label} request {r.uid}")


# --- the tentpole guarantees -------------------------------------------


def test_paged_matches_generate_and_compiles_once():
    """Bit-identical greedy outputs vs generate() across a mixed trace,
    with EXACTLY one chunk-prefill, one decode, and (at most) one
    block-copy compilation for the engine's lifetime — across TWO
    run() calls (the second starts with a warm prefix index)."""
    eng = _engine()
    reqs = _trace(n=5, max_new=(1, 8), plens=(3, 16))
    out = eng.run(reqs)
    assert not out["errors"]
    _check_parity(out, reqs, label="run1")
    s = out["stats"]
    assert s["chunk_compiles"] == 1, s
    assert s["decode_compiles"] == 1, s
    assert s["verify_compiles"] == 0, s

    reqs2 = _trace(seed=9, n=3)
    out2 = eng.run(reqs2)
    _check_parity(out2, reqs2, label="run2")
    s2 = out2["stats"]
    assert s2["chunk_compiles"] == 1 and s2["decode_compiles"] == 1, s2


def test_prefix_reuse_skips_prefill_same_tokens_out():
    """Requests opening with one shared system prompt: the paged engine
    prefills the shared blocks ONCE, later requests reference them
    (hit rate > 0, fewer prefill tokens computed) — and every output
    token still matches generate() exactly."""
    rng = np.random.default_rng(5)
    sys_prompt = rng.integers(1, 61, 17).astype(np.int32)
    reqs = []
    for uid in range(4):
        tail = rng.integers(1, 61, 4 + uid).astype(np.int32)
        reqs.append(Request(uid, np.concatenate([sys_prompt, tail]),
                            6, arrival_tick=0))
    eng = _engine(max_slots=2)
    out = eng.run(reqs)
    assert not out["errors"]
    _check_parity(out, reqs, label="shared-prefix")
    pg = out["stats"]["paged"]
    # requests 0-1 are admitted together into an empty index; 2-3 admit
    # after blocks committed and reuse the two full 8-blocks each (the
    # partial 3rd block may add more via the children index)
    assert pg["shared_tokens"] >= 2 * 16, pg
    assert pg["prefix_hit_rate"] > 0.3, pg
    assert pg["prefill_tokens_computed"] < pg["prompt_tokens"] + \
        8 * len(reqs), pg

    # a SECOND trace with the same system prompt through the same
    # engine starts with a warm index: the shared prefix is never
    # recomputed
    tail = rng.integers(1, 61, 5).astype(np.int32)
    reqs2 = [Request(10, np.concatenate([sys_prompt, tail]), 4,
                     arrival_tick=0)]
    out2 = eng.run(reqs2)
    _check_parity(out2, reqs2, label="warm-index")
    assert out2["stats"]["paged"]["shared_tokens"] >= 16


def test_copy_on_write_isolates_divergence():
    """Two prompts sharing a PARTIAL block (12 tokens, block size 8):
    the second matches mid-block, gets a copy-on-write reserve block,
    and neither request's outputs are perturbed by the other."""
    rng = np.random.default_rng(7)
    shared = rng.integers(1, 61, 12).astype(np.int32)
    a = Request(0, np.concatenate([shared,
                                   rng.integers(1, 61, 6).astype(np.int32)]),
                5, arrival_tick=0)
    # B arrives once A has committed (and registered) both blocks the
    # 12-token prefix spans — the partial match on block 1 is what
    # forces the copy
    b = Request(1, np.concatenate([shared,
                                   rng.integers(1, 61, 9).astype(np.int32)]),
                5, arrival_tick=4)
    eng = _engine(max_slots=2)
    out = eng.run([a, b])
    assert not out["errors"]
    _check_parity(out, [a, b], label="cow")
    assert out["stats"]["paged"]["cow_copies"] >= 1, out["stats"]["paged"]


def test_spec_decoding_exact_parity_fewer_target_forwards():
    """Speculative decoding with a truncated 1-layer draft: outputs are
    bit-identical to generate() (greedy parity is exact, acceptance only
    changes speed), the verify and draft programs compile once each, and
    plain decode never runs (``decode_compiles == 0``)."""
    reqs = _trace(seed=3, n=4, max_new=(4, 10), plens=(3, 14))
    eng = _engine(max_len=40, draft_layers=1, spec_k=3)
    out = eng.run(reqs)
    assert not out["errors"]
    _check_parity(out, reqs, label="spec")
    s = out["stats"]
    assert s["decode_compiles"] == 0, s
    assert s["verify_compiles"] == 1, s
    assert s["draft_compiles"] == 1, s
    assert s["chunk_compiles"] == 1, s
    sp = s["spec"]
    assert sp["enabled"] and sp["rounds"] > 0
    assert sp["acceptance_rate"] is not None
    assert 0.0 <= sp["acceptance_rate"] <= 1.0
    # every accepted proposal is one target forward the engine skipped
    assert sp["proposed"] == sp["rounds"] * 3


def test_chunked_prefill_bounds_decode_stalls():
    """The stall bound, tick by tick: while a 40-token prompt lands in
    8-token chunks, the already-live short request decodes EVERY tick —
    a long arrival costs live streams at most one chunk of compute per
    tick, never a whole prompt."""
    rng = np.random.default_rng(11)
    short = Request(0, rng.integers(1, 61, 4).astype(np.int32), 20,
                    arrival_tick=0)
    long_ = Request(1, rng.integers(1, 61, 40).astype(np.int32), 3,
                    arrival_tick=2)
    eng = _engine(max_slots=2, prefill_chunk=8)
    out = eng.run([short, long_], keep_timeline=True)
    assert not out["errors"]
    _check_parity(out, [short, long_], label="stall")
    tl = out["timeline"]
    # the long prompt takes ceil(40/8) = 5 chunk ticks
    chunk_ticks = [ev["tick"] for ev in tl if 1 in ev["chunks"]]
    assert len(chunk_ticks) == 5, tl
    # budget: at most chunks_per_tick (=1) chunks ever run in one tick
    assert all(len(ev["chunks"]) <= 1 for ev in tl)
    # THE bound: on every tick the long prompt was prefilling, the
    # short request still decoded
    short_decode_ticks = {ev["tick"] for ev in tl if 0 in ev["decoded"]}
    for t in chunk_ticks:
        assert t in short_decode_ticks, \
            f"tick {t}: short stalled behind long prefill\n{tl}"


def test_burst_of_long_prompts_cannot_starve_short():
    """Fairness regression: three 40-token prompts and one short
    request all admitted at tick 0.  The round-robin chunk budget
    guarantees the short request's single chunk runs within
    ``max_slots`` ticks and it decodes every tick after — a long-prompt
    burst delays it by a bounded number of chunks, not by the burst's
    total prefill work."""
    rng = np.random.default_rng(13)
    reqs = [Request(u, rng.integers(1, 61, 40).astype(np.int32), 2,
                    arrival_tick=0) for u in range(3)]
    reqs.append(Request(3, rng.integers(1, 61, 5).astype(np.int32), 8,
                        arrival_tick=0))
    eng = _engine(max_slots=4, prefill_chunk=8)
    out = eng.run(reqs, keep_timeline=True)
    assert not out["errors"]
    tl = out["timeline"]
    first_chunk = next(ev["tick"] for ev in tl if 3 in ev["chunks"])
    assert first_chunk < 4, \
        f"short request's prefill waited {first_chunk} ticks\n{tl}"
    # once live it decodes on EVERY subsequent tick until retirement,
    # long burst or not
    decoded = [ev["tick"] for ev in tl if 3 in ev["decoded"]]
    assert len(decoded) >= 1
    assert decoded == list(range(decoded[0], decoded[0] + len(decoded))), \
        f"short request skipped decode ticks: {decoded}"
    _check_parity(out, reqs, label="fairness")


@pytest.mark.slow
def test_admission_waits_for_blocks_never_deadlocks():
    """A trace larger than the block pool: admission reserves each
    request's WHOLE budget or waits, so the pool can never deadlock
    mid-request — everything completes, with evictions or head-of-line
    waits, and outputs stay exact."""
    reqs = _trace(seed=17, n=6, plens=(10, 18), max_new=(4, 8))
    # 2 slots x 6 blocks, +2 spare: admission must throttle
    eng = _engine(max_slots=2, num_blocks=14)
    out = eng.run(reqs)
    assert not out["errors"]
    assert len(out["results"]) == len(reqs)
    _check_parity(out, reqs, label="pressure")
    pg = out["stats"]["paged"]
    assert pg["blocks_peak_in_use"] <= 14


def test_request_longer_than_capacity_rejected():
    eng = _engine(max_slots=1)
    big = Request(0, np.ones(45, np.int32), 10, arrival_tick=0)
    out = eng.run([big])
    assert 0 in out["errors"]
    assert not out["results"]


# --- unit layers --------------------------------------------------------


def test_chain_hash_commits_to_whole_prefix():
    h1 = chain_hash(b"", [1, 2, 3])
    assert chain_hash(b"", [1, 2, 3]) == h1
    assert chain_hash(b"", [1, 2, 4]) != h1
    h2 = chain_hash(h1, [4, 5])
    # same chunk under a different parent → different chain hash
    assert chain_hash(chain_hash(b"", [9, 9, 9]), [4, 5]) != h2


def test_block_manager_refcounts_and_eviction():
    mgr = BlockManager(num_blocks=8, block_size=4, max_slots=2,
                       blocks_per_slot=4)
    prompt = list(range(1, 14))           # 13 tokens: 3 full blocks - 1
    sp = mgr.match_prefix(prompt)
    assert mgr.shared_len(sp) == 0        # cold index
    shared = mgr.admit(0, sp, 16)
    assert shared == 0 and mgr.in_use == 4
    mgr.register_committed(0, prompt, 12)
    mgr.release(0)
    # registered blocks outlive the request (index holds the ref) ...
    assert mgr.in_use == 3
    # ... and a matching prompt reuses them, capped at L-1 so the last
    # token is always recomputed for first-token sampling
    sp2 = mgr.match_prefix(prompt)
    assert mgr.shared_len(sp2) == 12      # 12 < 13 - 1 is false: 12 = L-1
    sp3 = mgr.match_prefix(prompt[:13])
    assert mgr.shared_len(sp3) <= len(prompt) - 1
    # filling the pool evicts LRU index blocks rather than failing
    shared2 = mgr.admit(0, sp2, 16)
    assert shared2 == 12
    mgr.release(0)
    sp4 = mgr.match_prefix([50, 51, 52, 53, 54])
    assert mgr.can_admit(sp4, 20) is False or mgr.in_use <= 8


def test_plan_chunks_tail_shift_single_width():
    # 19 unshared tokens in 8-token chunks: 0-8, 8-16, then the LAST
    # chunk slides back to keep one static width (feed 11..19)
    plans = plan_chunks(0, 19, 8)
    assert [(p.feed_start, p.commit_to) for p in plans] == \
        [(0, 8), (8, 16), (11, 19)]
    assert [p.is_last for p in plans] == [False, False, True]
    assert plans[-1].logit_index == 18 - 11
    # shared prefix shifts the start; a short remainder is one chunk
    plans = plan_chunks(12, 15, 8)
    assert [(p.feed_start, p.commit_to) for p in plans] == [(7, 15)]
    assert plans[0].logit_index == 14 - 7
    with pytest.raises(ValueError):
        plan_chunks(5, 5, 8)


def test_write_targets_route_overlap_to_trash():
    table = np.array([3, 7, 9, 2], np.int32)
    blocks, offsets, live = write_targets(
        feed_start=5, n=8, committed=8, length=11, table_row=table,
        block_size=4)
    # positions 5..7 are already committed, 11..12 beyond the prompt:
    # both land in the trash block; 8..10 write for real
    assert list(blocks[:3]) == [TRASH] * 3
    assert list(blocks[3:6]) == [9, 9, 9]
    assert list(offsets[3:6]) == [0, 1, 2]
    assert list(blocks[6:]) == [TRASH] * 2
    assert list(live) == [0, 0, 0, 1, 1, 1, 0, 0]


def test_greedy_accept_prefix_semantics():
    a, em = greedy_accept([5, 6, 7], [5, 6, 7, 8])
    assert (a, em) == (3, [5, 6, 7, 8])       # all accepted + bonus
    a, em = greedy_accept([5, 6, 7], [5, 9, 1, 2])
    assert (a, em) == (1, [5, 9])             # correction replaces d_1
    a, em = greedy_accept([5, 6, 7], [4, 1, 2, 3])
    assert (a, em) == (0, [4])                # pure fallback to target
    with pytest.raises(ValueError):
        greedy_accept([5, 6], [5, 6])


def test_truncated_draft_shares_weights():
    model, params = _shared()
    draft, dparams = truncated_draft(model.clone(decode=True), params, 1)
    assert draft.num_layers == 1
    assert dparams["embed"] is params["embed"]
    assert "layer_1" not in dparams
    with pytest.raises(ValueError):
        truncated_draft(model.clone(decode=True), params, 2)


# --- trace-driven load + SLOs ------------------------------------------


def test_make_load_shapes_and_determinism():
    spec = LoadSpec(n_requests=12, arrival="poisson", rate=1.5,
                    shared_prefix_len=6, shared_frac=1.0,
                    prompt_short=(2, 4), prompt_long=(8, 10),
                    slo_ttft_ms=100.0)
    a = make_load(spec, vocab_size=61, seed=4)
    b = make_load(spec, vocab_size=61, seed=4)
    assert [r.prompt.tolist() for r in a] == \
        [r.prompt.tolist() for r in b]
    head = a[0].prompt[:6].tolist()
    assert all(r.prompt[:6].tolist() == head for r in a)  # one sys prompt
    ticks = [r.arrival_tick for r in a]
    assert ticks == sorted(ticks)
    assert all(r.slo_ttft_ms == 100.0 for r in a)

    bursty = make_load(LoadSpec(n_requests=8, arrival="bursty",
                                burst_every=5, burst_size=4),
                       vocab_size=61, seed=0)
    assert sorted({r.arrival_tick for r in bursty}) == [0, 5]


def test_slo_report_counts_misses():
    reqs = [Request(0, np.ones(3, np.int32), 2, slo_ttft_ms=100.0),
            Request(1, np.ones(3, np.int32), 2, slo_e2e_ms=1000.0),
            Request(2, np.ones(3, np.int32), 2)]
    rep = slo_report(reqs, {0: 0.05, 1: 5.0}, {0: 0.2, 1: 0.5})
    assert rep["slo_checked"] == 2          # request 2 has no SLO
    assert rep["slo_attained"] == 2         # 1's TTFT is unconstrained
    rep = slo_report(reqs, {0: 0.25}, {0: 0.3, 1: 2.0})
    assert rep["slo_ttft_misses"] == 1      # 0 blew 100ms
    assert rep["slo_e2e_misses"] == 1       # 1 blew 1s
    assert rep["slo_attainment"] == 0.0
    # a request with an SLO but NO measurement is a miss, not a skip
    rep = slo_report(reqs, {}, {})
    assert rep["slo_checked"] == 2 and rep["slo_attained"] == 0
    assert slo_report([reqs[2]], {}, {})["slo_attainment"] is None


# --- CLI validation (satellite: parse-time, clear SystemExit) ----------


@pytest.mark.parametrize("argv,msg", [
    (["--max-slots", "0"], "--max-slots"),
    (["--max-slots", "-2"], "--max-slots"),
    (["--prefill-buckets", "8,8"], "duplicate"),
    (["--prefill-buckets", "16,8"], "ascending"),
    (["--draft", "1"], "--draft requires --paged"),
    (["--paged", "--draft", "-1"], "--draft"),
    (["--paged", "--slo-ttft-ms", "0"], "--slo-ttft-ms"),
])
def test_cli_rejects_bad_serving_flags(argv, msg):
    base = ["-l", "1", "-s", "32", "-e", "1", "-b", "16"]
    with pytest.raises(SystemExit, match=msg.replace("-", r"\-")):
        parse_args(base + argv, workload="gpt")


def test_cli_accepts_paged_flags():
    cfg = parse_args(["-l", "2", "-s", "32", "-e", "1", "-b", "16",
                      "--paged", "--kv-block-size", "8",
                      "--prefill-chunk", "16", "--draft", "1",
                      "--spec-k", "3", "--slo-ttft-ms", "500"],
                     workload="gpt")
    assert cfg.paged and cfg.kv_block_size == 8
    assert cfg.prefill_chunk == 16 and cfg.draft == 1 and cfg.spec_k == 3
    assert cfg.slo_ttft_ms == 500.0 and cfg.slo_e2e_ms is None


# --- bench harness (one place defines the load shapes) -----------------


def test_paged_serving_bench_record_fields():
    from distributed_deep_learning_tpu.serve.bench import \
        paged_serving_bench

    rec = paged_serving_bench(
        model_kw=MODEL, max_slots=2, kv_block_size=8, prefill_chunk=8,
        load_kw=dict(n_requests=4, arrival="front", rate=None,
                     prompt_short=(3, 6), prompt_long=(10, 16),
                     shared_prefix_len=6, shared_frac=0.5,
                     new_tokens=(2, 6), slo_ttft_ms=60000.0,
                     slo_e2e_ms=60000.0),
        compare_engine=False)
    pe = rec["paged_engine"]
    for key in ("prefix_hit_rate", "slo_attainment", "spec_acceptance",
                "chunk_compiles", "decode_compiles", "latency"):
        assert key in pe, key
    assert pe["decode_compiles"] == 1
    assert rec["errors"] == 0
    assert pe["slo"]["slo_checked"] == 4
