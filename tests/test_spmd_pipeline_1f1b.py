"""1F1B pipelined train pass: parity with the GPipe/autodiff path and the
O(S) activation-residency property (VERDICT item: cut all-microbatch
residency; the reference's scheduler is forward-only, MLP/model.py:81-130)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_deep_learning_tpu.parallel.spmd_pipeline import (
    one_f_one_b_schedule, spmd_pipeline, spmd_pipeline_1f1b,
    stack_stage_params)
from distributed_deep_learning_tpu.runtime.mesh import build_mesh

S, D = 4, 16


class Block(nn.Module):
    @nn.compact
    def __call__(self, h):
        return h + nn.Dense(D, kernel_init=nn.initializers.lecun_normal())(
            nn.relu(h))


@pytest.fixture(scope="module")
def setup():
    mesh = build_mesh({"stage": S, "data": 2})
    blk = Block()
    key = jax.random.key(0)
    h0 = jnp.zeros((1, D))
    trunk = stack_stage_params(
        [blk.init(jax.random.fold_in(key, i), h0)["params"]
         for i in range(S)])
    head = nn.Dense(8)
    x = jax.random.normal(jax.random.key(1), (16, D))
    y = jax.nn.one_hot(jax.random.randint(jax.random.key(2), (16,), 0, 8), 8)
    head_params = head.init(jax.random.key(3), x)["params"]
    stage_fn = lambda p, a: blk.apply({"params": p}, a)  # noqa: E731

    def head_loss(hp, h, tgt):
        logits = head.apply({"params": hp}, h)
        return jnp.mean(optax.softmax_cross_entropy(logits, tgt))

    return mesh, stage_fn, head_loss, trunk, head_params, x, y


def _reference_loss(setup_vals):
    """Same computation via spmd_pipeline + outer autodiff (GPipe path)."""
    mesh, stage_fn, head_loss, trunk, head_params, x, y = setup_vals

    def loss_fn(trunk, hp, x):
        h = spmd_pipeline(stage_fn, trunk, x, mesh=mesh, microbatch_size=4)
        return head_loss(hp, h, y)

    with mesh:
        loss, grads = jax.jit(jax.value_and_grad(
            loss_fn, argnums=(0, 1, 2)))(trunk, head_params, x)
    return loss, grads


def test_1f1b_matches_gpipe_loss_and_grads(setup):
    mesh, stage_fn, head_loss, trunk, head_params, x, y = setup
    with mesh:
        loss, tg, hg, dx = jax.jit(
            lambda t, hp, x, y: spmd_pipeline_1f1b(
                stage_fn, head_loss, t, hp, x, y, mesh=mesh,
                microbatch_size=4))(trunk, head_params, x, y)
    ref_loss, (ref_tg, ref_hg, ref_dx) = _reference_loss(setup)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6), tg, ref_tg)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6), hg, ref_hg)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                               rtol=2e-4, atol=1e-6)


def test_1f1b_sgd_step_trains(setup):
    """A few hand-rolled SGD steps with 1F1B grads reduce the loss."""
    mesh, stage_fn, head_loss, trunk, head_params, x, y = setup

    # lr 0.05, not 0.5: at 0.5 this toy problem diverges under the
    # GPipe reference gradients too (identical loss trajectory), so a
    # larger rate tests SGD stability, not 1F1B correctness
    @jax.jit
    def step(trunk, hp):
        loss, tg, hg, _ = spmd_pipeline_1f1b(
            stage_fn, head_loss, trunk, hp, x, y, mesh=mesh,
            microbatch_size=4)
        upd = lambda p, g: jax.tree.map(  # noqa: E731
            lambda a, b: a - 0.05 * b.astype(a.dtype), p, g)
        return loss, upd(trunk, tg), upd(hp, hg)

    with mesh:
        losses = []
        for _ in range(5):
            loss, trunk, head_params = step(trunk, head_params)
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_schedule_residency_bound():
    """Peak in-flight microbatches per stage is O(S), independent of M —
    the property GPipe-with-scan-transpose lacks (O(M) residency)."""
    M, St = 64, 8
    ops = one_f_one_b_schedule(M, St)
    # track live residuals per stage: +1 at its F tick, -1 at its B tick
    peak = {s: 0 for s in range(St)}
    live = {s: 0 for s in range(St)}
    for t, s, op, m in sorted(ops):
        live[s] += 1 if op == "F" else -1
        peak[s] = max(peak[s], live[s])
    assert all(live[s] == 0 for s in live)           # every F has its B
    assert max(peak.values()) <= 2 * St - 1          # O(S), not O(M)=64
    assert max(peak.values()) < M / 2


def test_schedule_is_complete_and_causal():
    M, St = 6, 4
    ops = one_f_one_b_schedule(M, St)
    fwd = {(s, m): t for t, s, op, m in ops if op == "F"}
    bwd = {(s, m): t for t, s, op, m in ops if op == "B"}
    assert len(fwd) == len(bwd) == M * St
    for m in range(M):
        for s in range(St):
            if s > 0:  # forward flows left→right, one tick per hop
                assert fwd[(s, m)] == fwd[(s - 1, m)] + 1
            if s < St - 1:  # backward flows right→left
                assert bwd[(s, m)] == bwd[(s + 1, m)] + 1
            # a stage backwards a microbatch only after forwarding it
            assert bwd[(s, m)] >= fwd[(s, m)]


def test_schedule_total_ticks():
    """T = M + 2S - 2 combined ticks; with M >> S the bubble fraction
    (2S-2)/(M+2S-2) vanishes."""
    M, St = 32, 4
    ops = one_f_one_b_schedule(M, St)
    T = max(t for t, *_ in ops) + 1
    assert T == M + 2 * St - 2


def test_cli_1f1b_schedule_trains(monkeypatch):
    """bert -m pipeline --pipeline-schedule 1f1b end-to-end, and its loss
    trajectory matches the GPipe schedule (same weights, same data)."""
    from distributed_deep_learning_tpu.utils.config import Config, Mode
    from distributed_deep_learning_tpu.workloads.base import run_workload
    from distributed_deep_learning_tpu.workloads.northstar import BERT_SPEC

    monkeypatch.setenv("DDL_DATA_LIMIT", "96")
    base = dict(mode=Mode.PIPELINE, num_layers=2, size=32, epochs=2,
                batch_size=16, num_stages=2, microbatch=8,
                learning_rate=1e-2)
    _, h_1f1b = run_workload(
        BERT_SPEC, Config(**base, pipeline_schedule="1f1b"))
    _, h_gpipe = run_workload(BERT_SPEC, Config(**base))
    l1 = [h.loss for h in h_1f1b if h.phase == "train"]
    lg = [h.loss for h in h_gpipe if h.phase == "train"]
    assert l1[-1] < l1[0]  # it learns
    # 2% trajectory band: the schedules are mathematically identical but
    # reduce in different orders, and per-step fp drift compounds over
    # an epoch of updates (single-step grad parity is asserted at 2e-4
    # in test_1f1b_matches_gpipe_loss_and_grads above)
    np.testing.assert_allclose(l1, lg, rtol=2e-2)  # same trajectory
    a1 = [h.accuracy for h in h_1f1b if h.phase == "train"]
    ag = [h.accuracy for h in h_gpipe if h.phase == "train"]
    np.testing.assert_allclose(a1, ag, rtol=1e-3, atol=0.5)


def test_cli_parses_pipeline_schedule():
    from distributed_deep_learning_tpu.utils.config import parse_args

    c = parse_args(["--pipeline-schedule", "1f1b"], workload="bert")
    assert c.pipeline_schedule == "1f1b"


def test_pipeline_mode_elastic_recovers(tmp_path, monkeypatch):
    """--elastic works in -m pipeline too (review regression: the elastic
    branch only existed in the data-mode path)."""
    import distributed_deep_learning_tpu.train.elastic as elastic_mod
    from distributed_deep_learning_tpu.utils.config import Config, Mode
    from distributed_deep_learning_tpu.workloads.base import run_workload
    from distributed_deep_learning_tpu.workloads.northstar import BERT_SPEC

    monkeypatch.setenv("DDL_DATA_LIMIT", "64")
    real_fit = elastic_mod.fit
    calls = {"n": 0}

    def flaky_fit(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected failure")
        return real_fit(*args, **kwargs)

    monkeypatch.setattr(elastic_mod, "fit", flaky_fit)
    config = Config(mode=Mode.PIPELINE, num_layers=2, size=32, epochs=1,
                    batch_size=16, num_stages=2, microbatch=8, elastic=True,
                    checkpoint_dir=str(tmp_path / "ck"))
    _, history = run_workload(BERT_SPEC, config)
    assert calls["n"] == 2
    assert "test" in [h.phase for h in history]


class DropBlock(nn.Module):
    """Residual block with real flax Dropout — the stochastic stage the
    dropout-under-1F1B tests pipeline."""

    @nn.compact
    def __call__(self, h, train: bool = False):
        h2 = nn.Dense(D, kernel_init=nn.initializers.lecun_normal())(
            nn.relu(h))
        h2 = nn.Dropout(0.5, deterministic=not train)(h2)
        return h + h2


def test_1f1b_dropout_matches_sequential_replay():
    """VERDICT r3 item 5: --dropout under 1F1B.  The pipeline derives
    key = fold_in(fold_in(rng, stage), mb) for forward AND the
    rematerialised backward; a hand-rolled sequential replay with the
    same keys must reproduce loss and gradients exactly."""
    mesh = build_mesh({"stage": S}, jax.devices()[:S])
    blk = DropBlock()
    key = jax.random.key(0)
    h0 = jnp.zeros((1, D))
    trunk = stack_stage_params(
        [blk.init(jax.random.fold_in(key, i), h0)["params"]
         for i in range(S)])
    head = nn.Dense(8)
    x = jax.random.normal(jax.random.key(1), (16, D))
    y = jax.nn.one_hot(jax.random.randint(jax.random.key(2), (16,), 0, 8), 8)
    head_params = head.init(jax.random.key(3), x)["params"]
    rng = jax.random.key(7)
    stage_fn = lambda p, a, k: blk.apply(  # noqa: E731
        {"params": p}, a, train=True, rngs={"dropout": k})

    def head_loss(hp, h, tgt):
        logits = head.apply({"params": hp}, h)
        return jnp.mean(optax.softmax_cross_entropy(logits, tgt))

    with mesh:
        loss, tg, hg, dx = jax.jit(
            lambda t, hp, x, y: spmd_pipeline_1f1b(
                stage_fn, head_loss, t, hp, x, y, mesh=mesh,
                microbatch_size=4, rng=rng))(trunk, head_params, x, y)

    M, mb = 4, 4

    def ref_loss(trunk, hp, x):
        total = 0.0
        for m in range(M):
            h = x[m * mb:(m + 1) * mb]
            for s in range(S):
                p = jax.tree.map(lambda l, s=s: l[s], trunk)
                h = stage_fn(p, h, jax.random.fold_in(
                    jax.random.fold_in(rng, s), m))
            total = total + head_loss(hp, h, y[m * mb:(m + 1) * mb])
        return total / M

    ref, (rtg, rhg, rdx) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2))(trunk, head_params, x)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6), tg, rtg)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6), hg, rhg)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(rdx),
                               rtol=2e-4, atol=1e-6)
