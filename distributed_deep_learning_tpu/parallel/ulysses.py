"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

The second of the two standard context-parallel schemes (the first, ring
attention, lives in :mod:`.ring_attention`): activations arrive sharded
over the SEQUENCE axis (each device owns T/S contiguous tokens of every
head); two ``all_to_all`` collectives reshape that into a HEAD sharding
(each device owns H/S full-sequence heads), attention runs locally as
plain full attention per head group, and a mirror all-to-all restores the
sequence sharding for the (sequence-local) MLP that follows.

Trade-offs vs ring attention, both exact:

* Ulysses sends activations twice (two all-to-alls of the full q/k/v/o
  volume) but computes attention in ONE dense local call — best when heads
  divide nicely over devices and the fused-kernel path matters (the local
  call can be the Pallas flash kernel).
* Ring keeps activations put and rotates K/V S times — communication
  proportional to K/V only, any head count, but the attention is an S-hop
  software pipeline.

The reference has no sequence parallelism of any kind (SURVEY.md §2.5);
both schemes here shard over the same declared ``seq`` mesh axis, so they
are drop-in alternatives behind the same model plumbing.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_deep_learning_tpu.runtime.shmap import shard_map


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                      mesh: Mesh, axis: str = "seq", causal: bool = False,
                      window: int | None = None,
                      key_valid: jnp.ndarray | None = None,
                      attention_fn=None) -> jnp.ndarray:
    """Exact attention on ``(B, T, H, D)`` q/k/v sharded over ``axis`` in T.

    ``attention_fn(q, k, v, causal=..., dtype=...)`` runs the local
    full-sequence attention per head group (default: the package's dense
    softmax; pass the flash adapter for the fused kernel).  ``window`` (a
    causal sliding-window size) is forwarded to the local call — after the
    head-scatter all-to-all every device holds the FULL sequence, so the
    inner kernel applies the band exactly as in the unsharded case.

    ``key_valid`` is an optional ``(B, T)`` boolean padding mask sharded
    over ``axis`` like K (VERDICT r4 item 4).  It has no head axis to
    scatter, so instead of riding the all-to-all it is ``all_gather``-ed
    along ``axis`` — B·T bools per device, negligible next to the q/k/v
    volume the all-to-alls already move — and handed to the inner kernel,
    which masks exactly as in the unsharded case.
    """
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    S = mesh.shape[axis]
    B, T, H, D = q.shape
    Tk = k.shape[1]
    if H % S:
        raise ValueError(f"{H} heads not divisible over {axis}={S} "
                         "(use ring attention for head counts the mesh "
                         "does not divide)")
    if T % S or Tk % S:
        raise ValueError(f"sequence lengths q={T}, k={Tk} must divide "
                         f"{axis}={S}; pad to a multiple")
    has_kv = key_valid is not None
    if has_kv and key_valid.shape != (B, Tk):
        raise ValueError(f"key_valid shape {key_valid.shape} != ({B}, {Tk})")

    if attention_fn is None:
        from distributed_deep_learning_tpu.models.transformer import (
            dot_product_attention)

        attention_fn = dot_product_attention

    in_specs = (P(None, axis), P(None, axis), P(None, axis)) \
        + ((P(None, axis),) if has_kv else ())

    @partial(shard_map, mesh=mesh, in_specs=in_specs,
             out_specs=P(None, axis), check_vma=False)
    def run(q, k, v, *maybe_kv):
        # local shapes: (B, T/S, H, D) — sequence-sharded, all heads
        def to_heads(x):
            # all_to_all: scatter the head axis, gather the sequence axis
            # → (B, T, H/S, D): full sequence, head-sharded
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        inner_kw = {} if window is None else {"window": window}
        if has_kv:
            # (B, T/S) → (B, T): every head group masks the full sequence
            inner_kw["key_valid"] = lax.all_gather(
                maybe_kv[0], axis, axis=1, tiled=True)
        oh = attention_fn(qh, kh, vh, causal=causal, dtype=qh.dtype,
                          **inner_kw)
        # mirror: scatter sequence back, gather heads
        return lax.all_to_all(oh, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    return run(q, k, v, *((key_valid,) if has_kv else ()))


def make_attention_fn(mesh: Mesh, axis: str = "seq", causal: bool = False,
                      inner=None):
    """Adapter: Ulysses SP as a ``MultiHeadAttention.attention_fn``
    (mirrors the ring and flash adapters).  ``inner`` optionally selects
    the local kernel (e.g. the flash adapter) — composition the ring
    scheme cannot offer."""
    forced_causal = causal

    def attn(q, k, v, *, mask=None, key_valid=None, causal=False,
             window=None, dtype=jnp.float32):
        if mask is not None:
            raise NotImplementedError(
                "ulysses attention supports key_valid padding masks and "
                "causal=...; arbitrary dense mask tensors are unsupported "
                "— a global (T, T) mask defeats sequence sharding")
        out = ulysses_attention(q, k, v, mesh=mesh, axis=axis,
                                causal=causal or forced_causal,
                                window=window, key_valid=key_valid,
                                attention_fn=inner)
        return out.astype(dtype)

    return attn
