"""Host data pipeline: prefetch overlap, clean shutdown, -w / --data-dir.

The reference overlaps host decode with device compute via DataLoader
worker processes (``CNN/main.py:165-179``); here the analogue is
``PrefetchLoader`` (background thread) + ``ImageFolderDataset`` decode
threads, wired through ``make_loaders`` and the ``--data-dir``/``-w`` flags.
"""

import threading
import time

import numpy as np
import pytest

from distributed_deep_learning_tpu.data.datasets import ArrayDataset
from distributed_deep_learning_tpu.data.loader import (DeviceLoader,
                                                       PrefetchLoader,
                                                       make_loaders)
from distributed_deep_learning_tpu.data.splits import train_val_test_split


@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("images")
    rng = np.random.default_rng(0)
    for cls, shade in (("cat", 60), ("dog", 180)):
        d = root / cls
        d.mkdir()
        for i in range(4):
            arr = np.full((20 + i, 24, 3), shade, np.uint8)
            arr += rng.integers(0, 20, arr.shape, dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.png")
    return str(root)


def _thread_count(prefix: str = "") -> list[threading.Thread]:
    return [t for t in threading.enumerate() if t.is_alive()]


def test_prefetch_full_iteration_matches_base():
    base = [(np.full((2, 3), i), np.full((2,), i)) for i in range(7)]
    out = list(PrefetchLoader(base, depth=3))
    assert len(out) == 7
    for (x, y), (bx, by) in zip(out, base):
        np.testing.assert_array_equal(x, bx)
        np.testing.assert_array_equal(y, by)


def test_prefetch_abandoned_iteration_stops_producer():
    """Early `break` (e.g. a crashed epoch) must not strand the producer
    thread on a full queue — round-1 ADVICE finding."""
    n_before = len(_thread_count())
    items = [(np.zeros(1), np.zeros(1))] * 100
    it = iter(PrefetchLoader(items, depth=1))
    next(it)
    it.close()  # abandon mid-epoch; generator finally-block must clean up
    deadline = time.monotonic() + 5.0
    while len(_thread_count()) > n_before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(_thread_count()) <= n_before, "producer thread leaked"


def test_prefetch_propagates_producer_error():
    def bad():
        yield (np.zeros(1), np.zeros(1))
        raise ValueError("decode failed")

    class Loader:
        def __iter__(self):
            return bad()

    with pytest.raises(ValueError, match="decode failed"):
        list(PrefetchLoader(Loader(), depth=2))


def test_make_loaders_prefetches_train_only(mesh8):
    ds = ArrayDataset(np.zeros((64, 4), np.float32),
                      np.zeros((64, 2), np.float32))
    splits = train_val_test_split(64, seed=0)
    train, val, test = make_loaders(ds, splits, 8, mesh8)
    assert isinstance(train, PrefetchLoader)
    assert isinstance(val, DeviceLoader) and isinstance(test, DeviceLoader)
    # epoch plumbing passes through the wrapper to the shuffling loader
    train.set_epoch(3)
    assert train.loader.epoch == 3
    xs = [x for x, _ in train]
    assert len(xs) == len(train) == len(train.loader)


def test_make_loaders_prefetch_disable(mesh8):
    ds = ArrayDataset(np.zeros((32, 4), np.float32),
                      np.zeros((32, 2), np.float32))
    splits = train_val_test_split(32, seed=0)
    train, _, _ = make_loaders(ds, splits, 8, mesh8, prefetch=0)
    assert isinstance(train, DeviceLoader)


def test_imagefolder_concurrent_decode_tiny_cache(image_root):
    """Hammer the shared LRU from many threads with an eviction-heavy cache;
    must neither crash nor corrupt results (round-1 ADVICE race)."""
    from distributed_deep_learning_tpu.data.imagefolder import (
        ImageFolderDataset)

    ds = ImageFolderDataset(image_root, image_size=8, num_workers=6,
                            max_cached_images=2)
    expect_x, expect_y = ds.batch(np.arange(8))
    for _ in range(10):
        x, y = ds.batch(np.arange(8))
        np.testing.assert_array_equal(x, expect_x)
        np.testing.assert_array_equal(y, expect_y)


def test_resnet_data_dir_end_to_end(image_root):
    """`resnet --data-dir ... -w 2` trains on real files: classes are
    discovered from the directory layout and drive the model head."""
    from distributed_deep_learning_tpu.utils.config import Config, Mode
    from distributed_deep_learning_tpu.workloads.northstar import (
        RESNET_SPEC, _resnet_model)

    config = Config(mode=Mode.SEQUENTIAL, data_dir=image_root, image_size=8,
                    num_workers=2, batch_size=2, epochs=1, size=18)
    ds = RESNET_SPEC.build_dataset(config)
    assert ds.classes == ["cat", "dog"]
    model = _resnet_model(config, ds)
    assert model.num_classes == 2
    assert model.small_inputs  # 8px decode → CIFAR stem

    from distributed_deep_learning_tpu.workloads.base import run_workload

    _, history = run_workload(RESNET_SPEC, config)
    phases = [h.phase for h in history]
    assert "train" in phases and "test" in phases


def test_cli_parses_data_dir_flags():
    from distributed_deep_learning_tpu.utils.config import parse_args

    c = parse_args(["--data-dir", "/tmp/x", "--image-size", "96", "-w", "4"],
                   workload="resnet")
    assert c.data_dir == "/tmp/x"
    assert c.image_size == 96
    assert c.num_workers == 4


def test_prefetch_overlap_positive():
    """VERDICT r4: prefetch must actually OVERLAP host batch formation
    with (simulated) device compute — elapsed well under the serial sum."""
    import time as _t

    from distributed_deep_learning_tpu.data.loader import PrefetchLoader

    n, cost = 6, 0.05

    class SlowProducer:
        def __iter__(self):
            for i in range(n):
                _t.sleep(cost)  # simulated decode/gather
                yield i

    t0 = _t.perf_counter()
    for _ in SlowProducer():
        _t.sleep(cost)          # simulated device step (serial baseline)
    serial = _t.perf_counter() - t0

    t0 = _t.perf_counter()
    for _ in PrefetchLoader(SlowProducer(), depth=2):
        _t.sleep(cost)
    overlapped = _t.perf_counter() - t0
    # perfect overlap -> ~serial/2 (+1 fill); require a real win with
    # slack for loaded CI machines
    assert overlapped < serial * 0.85, (overlapped, serial)
