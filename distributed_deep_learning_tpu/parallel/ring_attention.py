"""Ring attention: exact attention over sequences sharded across devices.

Context parallelism for long sequences — the capability the reference lacks
entirely (its only sequence model consumes 10-step windows,
``LSTM/dataset.py:25``; SURVEY.md §2.5 lists SP/CP as absent) but which a
TPU framework must treat as first-class: sequence length is the axis that
outgrows a single chip's HBM first.

Mechanism (Ring Attention with blockwise softmax): queries stay put, K/V
blocks rotate around the ``seq`` mesh axis with ``lax.ppermute`` over ICI;
each hop every device contracts its local queries against the visiting K/V
block and folds the result into an online-softmax accumulator
(running max ``m``, denominator ``l``, numerator ``acc`` — the
flash-attention recurrence), so the full (T×T) score matrix never
materialises and per-device memory is O(T/S · T/S) per hop.  After S hops
every query has seen every key exactly once and the result equals full
attention bit-for-near-bit.

Communication and compute overlap naturally: the ppermute for hop r+1 is
independent of hop r's contraction, so XLA can pipeline them over ICI.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.7 exports shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() well-defined


def _block_attention(q, k, v, m, l, acc, q_start, k_start, causal,
                     window=None):
    """Fold one visiting K/V block into the online-softmax accumulator.

    Shapes: q (B,H,Tq,D); k,v (B,H,Tk,D); m,l (B,H,Tq); acc (B,H,Tq,D).
    ``q_start``/``k_start`` are the blocks' global sequence offsets (for the
    causal / sliding-window mask across blocks).
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(q.shape[-1] * 1.0)
    if causal:
        q_pos = q_start + jnp.arange(q.shape[2])
        k_pos = k_start + jnp.arange(k.shape[2])
        mask = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            mask = jnp.logical_and(mask,
                                   q_pos[:, None] - k_pos[None, :] < window)
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None])
    new_l = l * correction + jnp.sum(p, axis=-1)
    new_acc = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return new_m, new_l, new_acc


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   mesh: Mesh, axis: str = "seq", causal: bool = False,
                   window: int | None = None,
                   batch_axes: tuple[str, ...] = ("data", "fsdp")
                   ) -> jnp.ndarray:
    """Exact multi-head attention with the sequence sharded over ``axis``.

    Args:
      q, k, v: global ``(B, T, H, D)`` arrays (sharded or not — the
        shard_map partitions them: T over `axis`, B over `batch_axes`).
      mesh: mesh containing `axis`; composes with data parallelism.
      causal: standard autoregressive mask, applied across blocks via
        global positions.
      window: optional causal sliding-window size (each query attends to
        its last ``window`` global positions).  Masked via the same
        global-position arithmetic as the causal mask; the hop-0 diagonal
        block guarantees every query row folds at least its own position
        first, so later fully-masked blocks contribute exp(-inf)=0.

    Returns ``(B, T, H, D)`` attention output, sharded like ``q``.
    """
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    S = mesh.shape[axis]
    B, T, H, D = q.shape
    if T % S:
        raise ValueError(f"sequence length {T} not divisible by {axis}={S}")

    spec = P(batch_axes, axis, None, None)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def run(q, k, v):
        # local blocks: (B', Tl, H, D) → (B', H, Tl, D)
        q_ = jnp.swapaxes(q, 1, 2)
        k_ = jnp.swapaxes(k, 1, 2)
        v_ = jnp.swapaxes(v, 1, 2)
        Tl = q_.shape[2]
        my = lax.axis_index(axis)
        q_start = my * Tl

        m0 = jnp.full(q_.shape[:3], NEG_INF, q_.dtype)
        l0 = jnp.zeros(q_.shape[:3], q_.dtype)
        acc0 = jnp.zeros_like(q_)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def hop(carry, r):
            k_blk, v_blk, m, l, acc = carry
            # the block visiting at hop r originated on device (my - r) mod S
            k_start = ((my - r) % S) * Tl
            m, l, acc = _block_attention(q_, k_blk, v_blk, m, l, acc,
                                         q_start, k_start, causal, window)
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            return (k_blk, v_blk, m, l, acc), None

        (_, _, m, l, acc), _ = lax.scan(
            hop, (k_, v_, m0, l0, acc0), jnp.arange(S))
        out = acc / l[..., None]
        return jnp.swapaxes(out, 1, 2)

    return run(q, k, v)


def make_attention_fn(mesh: Mesh, axis: str = "seq", causal: bool = False):
    """Adapter: ring attention as a ``MultiHeadAttention.attention_fn``.

    The causal mask is computed internally from global block positions (the
    (T×T) mask tensor the dense path builds would defeat the whole point),
    so pass ``causal=True`` HERE and leave the layer's ``causal=False``.
    Arbitrary (padding) masks are not supported yet — pad to block
    boundaries instead.
    """

    forced_causal = causal

    def attn(q, k, v, *, mask=None, key_valid=None, causal=False,
             window=None, dtype=jnp.float32):
        if mask is not None or key_valid is not None:
            raise NotImplementedError(
                "ring attention computes its causal mask internally from "
                "global positions; explicit mask tensors are unsupported "
                "(pad to block boundaries instead)")
        out = ring_attention(q, k, v, mesh=mesh, axis=axis,
                             causal=causal or forced_causal, window=window)
        return out.astype(dtype)

    return attn


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   causal: bool = False) -> jnp.ndarray:
    """Single-device reference: softmax(qkᵀ/√d)v on ``(B, T, H, D)``."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1] * 1.0)
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)
