"""Auto-parallelism planner CLI: search the plan lattice, write the artifact.

Wraps ``tune.search.run_search`` for one workload: enumerate the legal
(mesh x microbatch x remat x ZeRO x compress) lattice for the visible
devices, prune with the analytic HBM model, measure survivors with
successive halving, and write the winning plan as a versioned JSON
artifact a training run replays with ``--plan FILE``.  Prints ONE JSON
line (the search record).

    JAX_PLATFORMS=cpu python scripts/autotune.py mlp -b 32 --out mlp.plan.json
    python scripts/autotune.py gpt -l 2 -s 64 -b 16 --trials 8
    python scripts/autotune.py mlp --dry-run          # enumerate+prune only

``--dry-run`` stops before any compile (fast-tier smoke: lattice size,
analytic prune counts, budget).  Unknown flags pass through to the
workload's own CLI (``-b``, ``-l``, ``--dtype``, ...).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _script_env() -> None:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="search mesh x microbatch x remat x ZeRO plans for a "
                    "workload and write a --plan artifact")
    p.add_argument("workload", help="mlp|cnn|lstm|mnist|resnet|transformer|"
                                    "bert|moe|gpt")
    p.add_argument("--out", default=None,
                   help="plan artifact path (default: "
                        "autotune_<workload>.plan.json)")
    p.add_argument("--dry-run", action="store_true",
                   help="enumerate + analytic prune only; no compiles, no "
                        "trials")
    p.add_argument("--trials", type=int, default=16,
                   help="trial-pool cap after analytic ranking (0 = no cap)")
    p.add_argument("--trial-steps", type=int, default=4,
                   help="measured steps in the first halving rung "
                        "(doubles per rung)")
    p.add_argument("--budget-bytes", type=int, default=None,
                   help="override the per-device HBM budget (backends "
                        "without memory_stats, e.g. the CPU test mesh, "
                        "never prune without this)")
    p.add_argument("--full-space", action="store_true",
                   help="search ZeRO/compress/accumulation too (default: "
                        "mesh x remat only — the cheap, always-relevant "
                        "axes)")
    p.add_argument("--calibration", default=None, metavar="FILE",
                   help="measured memory-model calibration artifact "
                        "(tune.calibrate) — its fitted ACT_FRACTION/"
                        "RECOMPUTE_COST constants replace the analytic "
                        "tables for pruning and ranking; stale artifacts "
                        "(foreign schema/key) are an error, a missing "
                        "file falls back to the analytic model")
    args, rest = p.parse_known_args(argv)

    _script_env()
    from distributed_deep_learning_tpu.tune import artifact, memory, space
    from distributed_deep_learning_tpu.utils.config import parse_args
    from distributed_deep_learning_tpu.workloads import get_spec

    spec = get_spec(args.workload)
    config = parse_args(rest, workload=args.workload)
    space_options = None if args.full_space else dict(
        zero_options=("none", "fsdp"), compress_options=("none",),
        grad_accum_options=(1,))

    from distributed_deep_learning_tpu.workloads.base import _devices

    devices = _devices(config)
    n = len(devices)

    if args.dry_run:
        # no model build, no compile: the lattice + the analytic model only
        plans = space.enumerate_plans(
            n, config.batch_size,
            **(space_options or {"dtypes": (config.dtype,)}))
        geom = memory.ModelGeometry(
            param_count=config.size * config.size * config.num_layers,
            num_layers=max(1, config.num_layers),
            layer_act_elems_per_example=config.size * 4)
        budget = memory.hbm_budget(devices, override=args.budget_bytes)
        feasible, rejected = memory.prune_plans(
            plans, geom, config.batch_size, budget)
        print(json.dumps({
            "workload": args.workload, "dry_run": True, "n_devices": n,
            "n_candidates": len(plans), "n_feasible": len(feasible),
            "n_pruned_analytic": len(rejected), "budget_bytes": budget,
        }))
        return 0

    from distributed_deep_learning_tpu.tune.search import run_search

    calibration = None
    if args.calibration:
        from distributed_deep_learning_tpu.tune import calibrate

        cal_key = calibrate.calibration_key(
            spec.name, config, n, devices[0].platform,
            getattr(devices[0], "device_kind", ""))
        calibration = calibrate.maybe_load_calibration(
            args.calibration, expected_key=cal_key)

    result = run_search(
        spec, config, devices=devices, trial_steps=args.trial_steps,
        max_trials=args.trials or None, budget_bytes=args.budget_bytes,
        space_options=space_options, calibration=calibration)
    key = artifact.plan_key(spec.name, config, n, devices[0].platform,
                            getattr(devices[0], "device_kind", ""))
    out = args.out or f"autotune_{spec.name}.plan.json"
    artifact.save_plan(out, result.best, key=key, workload=spec.name,
                       topology={"n_devices": n,
                                 "platform": devices[0].platform},
                       search=result.record())
    record = result.record()
    record["artifact"] = out
    if args.calibration:
        record["calibration"] = {"path": args.calibration,
                                 "loaded": calibration is not None}
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
