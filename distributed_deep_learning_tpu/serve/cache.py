"""Slot-based static KV cache: one allocation, any request mix.

The model's decode cache (:class:`..models.transformer.MultiHeadAttention`
``decode=True``) is a per-call pytree shaped ``(B, total_len, Hkv, D)``
with ONE scalar ``cache_index`` shared by all rows — correct for
batch-synchronous :func:`..models.transformer.generate`, useless for
continuous batching where every sequence sits at its own position.

This module re-hosts that exact cache as a SLOT TABLE: each array leaf
gains a leading ``max_slots`` axis and loses the per-call batch axis
(``cached_key``: ``(max_slots, max_len, Hkv, D)`` per layer), and each
scalar counter (``cache_index``, ``pos_index``) becomes a ``(max_slots,)``
vector — per-slot positions, the whole point.  Nothing about the model's
cache semantics is reimplemented: the engine vmaps the model's own
single-sequence decode over the slot axis (:func:`lift` / :func:`unlift`
move one slot between table layout and the model's ``B=1`` layout), so
slot decode is correct BY CONSTRUCTION — it is literally the tested
decode path, batched over slots.

All shapes here are static: requests enter and leave slots by writing
into this table (:func:`write_slot`), never by changing an array shape,
which is what lets the decode step compile once and be reused for the
engine's lifetime.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_deep_learning_tpu.models.transformer import init_cache

#: cache-collection leaf names that are sequence-position counters; these
#: are the leaves prefill must pin to the TRUE prompt length after a
#: bucket-padded forward (fix_counters) and that become (max_slots,)
#: vectors in the slot table.
COUNTER_LEAVES = ("cache_index", "pos_index")

#: cache-collection leaf names that hold actual key/value tensors — the
#: leaves the serving quantization path (:mod:`.quant`) stores in reduced
#: precision.  ``cached_valid`` (bool) and the counters stay exact.
KV_LEAVES = ("cached_key", "cached_value")


def _leaf_name(path) -> str:
    last = path[-1]
    return last.key if hasattr(last, "key") else str(last)


def allocate_slots(lm, max_slots: int, max_len: int,
                   token_dtype=jnp.int32):
    """Zeroed slot table for ``max_slots`` sequences of up to ``max_len``.

    Built from the decode model's own cache shapes (``eval_shape`` of a
    ``(1, max_len)`` init — no forward, no parameter init): array leaves
    swap their ``B=1`` axis for a ``max_slots`` axis, scalar counters
    become ``(max_slots,)``.
    """
    per_slot = init_cache(lm, 1, max_len, token_dtype)

    def alloc(leaf):
        if leaf.ndim == 0:                      # scalar counter
            return jnp.zeros((max_slots,), leaf.dtype)
        return jnp.zeros((max_slots,) + leaf.shape[1:], leaf.dtype)

    return jax.tree.map(alloc, per_slot)


def fresh_slot(slots):
    """A zeroed model-layout (``B=1``) cache matching one slot of
    ``slots`` — the blank cache a prefill forward fills in.  Pure shape
    work, so it is free inside a jitted prefill program."""
    def one(leaf):
        if leaf.ndim == 1:                      # (max_slots,) counter
            return jnp.zeros((), leaf.dtype)
        return jnp.zeros((1,) + leaf.shape[1:], leaf.dtype)

    return jax.tree.map(one, slots)


def lift(slot_cache):
    """One slot's leaves (no batch axis, scalar counters) -> the model's
    ``B=1`` cache layout.  Used under ``vmap`` over the slot axis."""
    return jax.tree.map(lambda x: x[None] if jnp.ndim(x) else x,
                        slot_cache)


def unlift(cache):
    """Inverse of :func:`lift`: drop the ``B=1`` axis, keep scalars."""
    return jax.tree.map(lambda x: x[0] if jnp.ndim(x) else x, cache)


def write_slot(slots, cache, slot, quantizer=None):
    """Write a model-layout (``B=1``) ``cache`` into row ``slot`` of the
    table.  ``slot`` may be traced (an int32 scalar), so one compiled
    prefill program serves every slot.

    Precision contract: a floating-point update may land in a LOWER
    floating precision slab (bf16 — the cast IS the quantization), but
    writing it into an INTEGER slab through a bare ``astype`` would
    silently round-and-wrap with no scale.  Integer slabs therefore
    require ``quantizer`` (a leaf map producing the slab's exact dtype,
    normally built on :mod:`.quant`'s scale-aware path); without one the
    write raises instead of corrupting the cache.
    """
    def wr(slab, upd):
        if slab.ndim == 1:                      # counter vector <- scalar
            upd = jnp.reshape(upd, (1,)).astype(slab.dtype)
            return jax.lax.dynamic_update_slice(slab, upd, (slot,))
        if jnp.issubdtype(slab.dtype, jnp.integer) and \
                jnp.issubdtype(upd.dtype, jnp.floating):
            if quantizer is None:
                raise TypeError(
                    f"write_slot: float {upd.dtype} update into an "
                    f"integer {slab.dtype} slab — a bare astype would "
                    "truncate without a scale; pass quantizer= (the "
                    "scale-aware serve.quant path)")
            upd = quantizer(upd)
            if upd.dtype != slab.dtype:
                raise TypeError(
                    f"write_slot: quantizer produced {upd.dtype}, "
                    f"slab holds {slab.dtype}")
        starts = (slot,) + (0,) * (slab.ndim - 1)
        return jax.lax.dynamic_update_slice(slab, upd.astype(slab.dtype),
                                            starts)

    return jax.tree.map(wr, slots, cache)


def fix_counters(cache, value):
    """Pin every position counter in a model-layout cache to ``value``.

    A bucket-padded prefill advances ``cache_index``/``pos_index`` by the
    PADDED length; resetting them to the true prompt length makes the
    next decode token overwrite the first pad position and take the
    correct (learned or rotary) position — bucket padding then has no
    numerical trace at all (the tail garbage K/V sit at positions the
    causal prefix mask can never reach before they are overwritten).
    """
    def fix(path, leaf):
        if _leaf_name(path) in COUNTER_LEAVES:
            return jnp.broadcast_to(jnp.asarray(value, leaf.dtype),
                                    leaf.shape)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)
